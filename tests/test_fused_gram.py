"""Fused Gram+solve training kernel: parity, routing, one-dispatch.

Contracts under test (the perf-opt PR's safety net, all in Pallas
interpret mode on the CPU tier-1 mesh):

- the fused gather+Gram+CG kernel (`ops/pallas_kernels.
  als_fused_solve_cg_pallas`) is a drop-in for the unfused
  `_gram_rhs_nnz` → `_reg_solve` assembly at EVERY fold-in ladder
  bucket width, explicit AND implicit, warm-start on and off — and
  through the `_solve_bucket_chunked` fallback boundary;
- routing: `PIO_ALS_FUSED_GRAM` + the VMEM table budget decide, per
  half-sweep side, fused-gather vs two-stage kernel vs XLA — resolved
  outside every trace;
- full-training parity: als_train / the implicit sweep with the fused
  kernel forced on reach the XLA path's fit (planted recovery);
- the one-dispatch continuation retrain: deferred plan splices are
  scattered INSIDE the training dispatch, bitwise-identical to the
  eager splice path, with the dispatch count == 1 pinned by
  `stats["train_dispatches"]` and the jit cache stable across
  same-shape retrains;
- `_cg_solve_spd`'s device-side residual early exit stops early on
  well-conditioned systems and is bit-identical to the fixed-budget
  path when it cannot trigger;
- the fold-in solver's ladder buckets route through the SAME fused
  kernel and still match the dense numpy reference.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from incubator_predictionio_tpu.ops import als, retrain
from incubator_predictionio_tpu.ops.pallas_kernels import (
    als_fused_fits,
    als_fused_solve_cg_pallas,
)

#: the speed layer's default fold-in bucket ladder (speed/foldin.py)
LADDER = (8, 32, 128, 512)


@pytest.fixture(autouse=True)
def _fresh_plans():
    retrain.drop_plans()
    yield
    retrain.drop_plans()


def _problem(seed, M, K, B, D, density=0.8):
    rng = np.random.default_rng(seed)
    table = rng.normal(0, 0.3, (M, K)).astype(np.float32)
    cols = rng.integers(0, M, (B, D)).astype(np.int32)
    vals = rng.normal(3.5, 1.0, (B, D)).astype(np.float32)
    mask = (rng.random((B, D)) < density).astype(np.float32)
    mask[min(3, B - 1)] = 0.0  # an empty row must solve to exactly 0
    x0 = rng.normal(0, 0.3, (B, K)).astype(np.float32)
    return table, cols, vals, mask, x0


def _unfused_reference(table, cols, vals, mask, l2, implicit, alpha,
                       cg_iters, x0):
    """THE unfused path: _gram_rhs_nnz → _reg_solve, f32 HIGHEST."""
    t = jnp.asarray(table)
    yty = (als._gram_all(t, jax.lax.Precision.HIGHEST)
           if implicit else None)
    gram, rhs, nnz = als._gram_rhs_nnz(
        t, jnp.asarray(cols), jnp.asarray(vals), jnp.asarray(mask),
        jnp.float32, jax.lax.Precision.HIGHEST, implicit=implicit,
        alpha=alpha)
    return yty, als._reg_solve(
        gram, rhs, nnz, l2, True, implicit=implicit, yty=yty,
        cg_iters=cg_iters, x0=None if x0 is None else jnp.asarray(x0))


class TestFusedKernelDifferential:
    """Fused gather+Gram+CG vs the unfused assembly, every ladder width."""

    @pytest.mark.parametrize("width", LADDER)
    @pytest.mark.parametrize("implicit", [False, True])
    @pytest.mark.parametrize("warm", [False, True])
    def test_matches_unfused_path(self, width, implicit, warm):
        table, cols, vals, mask, x0 = _problem(
            seed=width + implicit * 7 + warm, M=150, K=24, B=9, D=width)
        yty, ref = _unfused_reference(
            table, cols, vals, mask, 0.05, implicit, 2.0, 16,
            x0 if warm else None)
        got = als_fused_solve_cg_pallas(
            jnp.asarray(table), jnp.asarray(cols), jnp.asarray(vals),
            jnp.asarray(mask), 0.05, reg_nnz=True,
            iters=16 * (2 if implicit else 1), implicit=implicit,
            alpha=2.0, yty=yty,
            x0=jnp.asarray(x0) if warm else None, interpret=True)
        rel = float(jnp.max(jnp.abs(ref - got))
                    / (jnp.max(jnp.abs(ref)) + 1e-9))
        assert rel < 2e-5, (width, implicit, warm, rel)
        # the empty row is EXACTLY zero, warm start or not (the
        # _reg_solve where-guard parity)
        assert bool(jnp.all(got[3] == 0.0))

    def test_no_reg_nnz_and_rank_128_no_pad(self):
        """Plain-λ ridge + an already-lane-aligned rank (the production
        shape: no padding copies at all). With D=32 < K=128 the Gram is
        rank-deficient and only the λ ridge conditions it, so the two
        CG orderings legitimately diverge more — a stout λ keeps the
        comparison about the assembly, not the conditioning."""
        table, cols, vals, mask, _ = _problem(seed=2, M=160, K=128, B=8,
                                              D=32)
        t = jnp.asarray(table)
        gram, rhs, nnz = als._gram_rhs_nnz(
            t, jnp.asarray(cols), jnp.asarray(vals), jnp.asarray(mask),
            jnp.float32, jax.lax.Precision.HIGHEST, implicit=False,
            alpha=0.0)
        ref = als._reg_solve(gram, rhs, nnz, 0.5, False, implicit=False,
                             yty=None, cg_iters=32)
        got = als_fused_solve_cg_pallas(
            t, jnp.asarray(cols), jnp.asarray(vals), jnp.asarray(mask),
            0.5, reg_nnz=False, iters=32, interpret=True)
        rel = float(jnp.max(jnp.abs(ref - got))
                    / (jnp.max(jnp.abs(ref)) + 1e-9))
        assert rel < 1e-4, rel

    def test_chunked_fallback_boundary(self, monkeypatch):
        """Buckets past the chunk budget split under lax.map and must
        agree with the single-shot fused solve (the VMEM-budget
        fallback the sweep relies on for huge buckets)."""
        table, cols, vals, mask, x0 = _problem(seed=3, M=120, K=16, B=26,
                                               D=32)
        t = jnp.asarray(table)

        def solver(tt):
            return als._solve_bucket_fused(
                t, None, tt[0], tt[1], tt[2], 0.05, reg_nnz=True,
                cg_iters=8, x0=tt[3] if len(tt) > 3 else None)

        one_shot = als._solve_bucket_chunked(
            solver, jnp.asarray(cols), jnp.asarray(vals),
            jnp.asarray(mask), 16, x0=jnp.asarray(x0))
        monkeypatch.setattr(als, "_CHUNK_ELEMS", 1)  # force row chunks
        chunked = als._solve_bucket_chunked(
            solver, jnp.asarray(cols), jnp.asarray(vals),
            jnp.asarray(mask), 16, x0=jnp.asarray(x0))
        np.testing.assert_array_equal(np.asarray(one_shot),
                                      np.asarray(chunked))


class TestFusedRouting:
    def test_vmem_budget_gates_fused_sides(self, monkeypatch):
        monkeypatch.setenv("PIO_ALS_FUSED_GRAM", "on")
        monkeypatch.setattr(als, "_ALS_KERNEL", "on")
        # generous budget: both sides fit at this tiny shape
        monkeypatch.setenv("PIO_ALS_FUSED_VMEM_MB", "10")
        assert als._fused_sides(50, 40, False, False, jnp.float32, 8) \
            == (True, True)
        # a budget smaller than any table: nothing routes fused
        monkeypatch.setenv("PIO_ALS_FUSED_VMEM_MB", "0.000001")
        assert als._fused_sides(50, 40, False, False, jnp.float32, 8) \
            == (False, False)
        assert not als_fused_fits(26744, 128, jnp.float32) or \
            als_fused_fits(26744, 128, jnp.bfloat16)

    def test_ml20m_shape_budget_math(self):
        """The documented routing at the bench shape: the item table
        (26.7k × 128 bf16 ≈ 6.9 MB) fits the 10 MB default budget, the
        user table (138k × 128) does not — so the user half-sweep runs
        fully fused and the item half-sweep keeps the two-stage path."""
        assert als_fused_fits(26744, 128, jnp.bfloat16)
        assert not als_fused_fits(138493, 128, jnp.bfloat16)
        assert not als_fused_fits(138493, 128, jnp.float32)

    def test_over_budget_side_falls_back_to_two_stage(self, monkeypatch):
        """With fused enabled but the table over budget, wide explicit
        buckets still route through the two-stage kernel."""
        calls = {"fused": 0, "two_stage": 0}
        real_fused = als._solve_bucket_fused
        real_two = als._solve_bucket_kernel

        def spy_fused(*a, **k):
            calls["fused"] += 1
            return real_fused(*a, **k)

        def spy_two(*a, **k):
            calls["two_stage"] += 1
            return real_two(*a, **k)

        monkeypatch.setattr(als, "_solve_bucket_fused", spy_fused)
        monkeypatch.setattr(als, "_solve_bucket_kernel", spy_two)
        monkeypatch.setattr(als, "_ALS_KERNEL", "on")
        monkeypatch.setattr(als, "_KERNEL_MIN_D", 0)
        monkeypatch.setenv("PIO_ALS_FUSED_GRAM", "on")
        monkeypatch.setenv("PIO_ALS_FUSED_VMEM_MB", "0.000001")
        rng = np.random.default_rng(0)
        users = rng.integers(0, 30, 400).astype(np.int32)
        items = rng.integers(0, 20, 400).astype(np.int32)
        vals = rng.normal(3, 1, 400).astype(np.float32)
        als.als_train(users, items, vals, 30, 20, rank=4, iterations=1,
                      l2=0.05)
        assert calls["two_stage"] > 0 and calls["fused"] == 0
        jax.clear_caches()  # the spies are baked into this trace


class TestFusedTrainingParity:
    def test_als_train_fused_reaches_xla_fit(self, monkeypatch):
        rng = np.random.default_rng(7)
        n_u, n_i, k_true, nnz = 80, 50, 4, 3000
        u = rng.normal(0, 1, (n_u, k_true)).astype(np.float32)
        v = rng.normal(0, 1, (n_i, k_true)).astype(np.float32)
        users = rng.integers(0, n_u, nnz).astype(np.int32)
        items = rng.integers(0, n_i, nnz).astype(np.int32)
        ratings = np.einsum("nk,nk->n", u[users], v[items]).astype(
            np.float32)
        kw = dict(n_users=n_u, n_items=n_i, rank=8, iterations=6,
                  l2=0.02, bf16_sweeps=3)
        monkeypatch.setattr(als, "_ALS_KERNEL", "off")
        st_xla, _ = als.als_train(users, items, ratings, **kw)
        monkeypatch.setattr(als, "_ALS_KERNEL", "on")
        monkeypatch.setattr(als, "_KERNEL_MIN_D", 0)
        monkeypatch.setenv("PIO_ALS_FUSED_GRAM", "on")
        st_fused, _ = als.als_train(users, items, ratings, **kw)
        r_xla = als.rmse(st_xla, users, items, ratings)
        r_fused = als.rmse(st_fused, users, items, ratings)
        assert r_fused < max(1.15 * r_xla, r_xla + 0.02), (r_fused, r_xla)
        assert r_fused < 0.1, r_fused

    def test_implicit_half_sweep_matches_xla(self, monkeypatch):
        """One implicit half-sweep, fused kernel vs XLA assembly —
        implicit mode is kernel-eligible ONLY in the fused generation
        (the shared-YᵗY operand), so this is its first kernel parity
        pin."""
        rng = np.random.default_rng(9)
        n_rows, n_other, rank = 40, 30, 8
        other = jnp.asarray(
            rng.normal(0, 0.3, (n_other, rank)).astype(np.float32))
        users = rng.integers(0, n_rows, 600).astype(np.int64)
        items = rng.integers(0, n_other, 600).astype(np.int64)
        w = np.abs(rng.normal(1, 1, 600)).astype(np.float32)
        from incubator_predictionio_tpu.ops.sparse import build_both_sides

        (light, heavy), _ = build_both_sides(users, items, w, n_rows,
                                             n_other)
        tree = als._buckets_tree(light)
        hv = als._heavy_tree(heavy)
        kw = dict(l2=0.05, alpha=2.0, reg_nnz=True,
                  compute_dtype=jnp.float32,
                  precision=jax.lax.Precision.HIGHEST, implicit=True,
                  cg_iters=16)
        ref = als._sweep_side(n_rows, other, tree, hv, **kw)
        monkeypatch.setattr(als, "_ALS_KERNEL", "on")
        got = als._sweep_side(n_rows, other, tree, hv, use_kernel=True,
                              use_fused=True, kernel_min_d=0, **kw)
        rel = float(jnp.max(jnp.abs(ref - got))
                    / (jnp.max(jnp.abs(ref)) + 1e-9))
        assert rel < 2e-5, rel

    def test_als_train_implicit_fused_finite_and_ranks(self, monkeypatch):
        monkeypatch.setattr(als, "_ALS_KERNEL", "on")
        monkeypatch.setattr(als, "_KERNEL_MIN_D", 0)
        monkeypatch.setenv("PIO_ALS_FUSED_GRAM", "on")
        rng = np.random.default_rng(11)
        users = rng.integers(0, 30, 800).astype(np.int32)
        items = rng.integers(0, 20, 800).astype(np.int32)
        w = np.abs(rng.normal(1, 1, 800)).astype(np.float32)
        st = als.als_train_implicit(users, items, w, 30, 20, rank=4,
                                    iterations=3, l2=0.05, alpha=2.0)
        uf = np.asarray(st.user_factors)
        assert uf.shape == (30, 4) and np.all(np.isfinite(uf))


class TestCgEarlyExit:
    def _spd(self, seed=0, B=6, K=12):
        rng = np.random.default_rng(seed)
        a = rng.normal(0, 1, (B, K, K)).astype(np.float32)
        a = np.einsum("bik,bjk->bij", a, a) + 0.0  # SPD-ish
        b = rng.normal(0, 1, (B, K)).astype(np.float32)
        lam = np.full(B, 2.0, np.float32)
        return jnp.asarray(a), jnp.asarray(b), jnp.asarray(lam)

    def test_early_exit_stops_and_matches_full_budget(self):
        a, b, lam = self._spd()
        full = als._cg_solve_spd(a, b, 64, lam=lam)
        x, iters = als._cg_solve_spd(a, b, 64, lam=lam, tol=1e-6,
                                     return_iters=True)
        assert int(iters) < 64, int(iters)
        np.testing.assert_allclose(np.asarray(x), np.asarray(full),
                                   atol=1e-4, rtol=1e-4)

    def test_untriggered_tol_is_bitwise_fixed_budget(self):
        """The while_loop path with a tolerance too small to fire runs
        the exact fixed budget — bit-identical to the fori_loop path
        (the parity pin the satellite asks for)."""
        a, b, lam = self._spd(seed=1)
        fixed = als._cg_solve_spd(a, b, 8, lam=lam, tol=0.0)
        # tol² underflows to 0 → the exit can only fire at rz == 0.0,
        # where the division guards freeze x anyway
        loose = als._cg_solve_spd(a, b, 8, lam=lam, tol=1e-300)
        np.testing.assert_array_equal(np.asarray(fixed),
                                      np.asarray(loose))

    def test_env_knob_threads_through_training(self, monkeypatch):
        monkeypatch.setenv("PIO_ALS_CG_TOL", "1e-5")
        rng = np.random.default_rng(4)
        users = rng.integers(0, 25, 500).astype(np.int32)
        items = rng.integers(0, 15, 500).astype(np.int32)
        vals = rng.normal(3, 1, 500).astype(np.float32)
        st, _ = als.als_train(users, items, vals, 25, 15, rank=4,
                              iterations=3, l2=0.05)
        assert np.all(np.isfinite(np.asarray(st.user_factors)))


class TestOneDispatchRetrain:
    def _coo(self, rng, n, nu=40, ni=25):
        return (rng.integers(0, nu, n), rng.integers(0, ni, n),
                rng.normal(3, 1, n).astype(np.float32))

    def test_steady_state_retrain_is_one_dispatch(self):
        rng = np.random.default_rng(6)
        users, items, vals = self._coo(rng, 1200)
        base = retrain.als_retrain(users, items, vals, 40, 25, rank=4,
                                   iterations=4, l2=0.05, seed=0,
                                   tol=0.0, plan_key="od")
        t_u, t_i, t_v = self._coo(rng, 90)
        u2 = np.concatenate([users, t_u])
        i2 = np.concatenate([items, t_i])
        v2 = np.concatenate([vals, t_v])
        stats: dict = {}
        retrain.als_retrain(u2, i2, v2, 40, 25, rank=4, iterations=4,
                            l2=0.05, seed=0, prev_state=base, tol=0.0,
                            plan_key="od", stats=stats)
        assert stats["prep_plan"] == "reused"
        assert stats["mode"] == "continue"
        assert stats["train_dispatches"] == 1, stats
        assert stats["one_dispatch"] is True

    def test_zero_iteration_retrain_still_applies_splice(self):
        """A deferred splice produced by prep must reach the plan's
        resident trees even when NO training leg runs (iterations=0):
        committing pre-splice trees while the plan digest already
        covers the tail would silently drop the tail's interactions
        from every future reuse."""
        rng = np.random.default_rng(11)
        users, items, vals = self._coo(rng, 1200)
        retrain.als_retrain(users, items, vals, 40, 25, rank=4,
                            iterations=4, l2=0.05, seed=0, tol=0.0,
                            plan_key="od0")
        t_u, t_i, t_v = self._coo(rng, 90)
        u2 = np.concatenate([users, t_u])
        i2 = np.concatenate([items, t_i])
        v2 = np.concatenate([vals, t_v])
        stats: dict = {}
        retrain.als_retrain(u2, i2, v2, 40, 25, rank=4, iterations=0,
                            l2=0.05, seed=0, tol=0.0, plan_key="od0",
                            stats=stats)
        assert stats["prep_plan"] == "reused"
        # the committed residents (re-fetched via a zero-delta reuse)
        # must be bitwise-identical to an eager-splice reuse of the
        # same base plan + tail
        stats2: dict = {}
        u_res, i_res, _, _ = retrain.prepare_with_reuse(
            u2, i2, v2, 40, 25, plan_key="od0", stats=stats2)
        assert stats2["prep_plan"] == "reused"
        retrain.prepare_with_reuse(users, items, vals, 40, 25,
                                   plan_key="od0e", stats={})
        u_eag, i_eag, _, _ = retrain.prepare_with_reuse(
            u2, i2, v2, 40, 25, plan_key="od0e", stats={})
        for side_a, side_b in ((u_res, u_eag), (i_res, i_eag)):
            assert len(side_a) == len(side_b)
            for bucket_a, bucket_b in zip(side_a, side_b):
                for arr_a, arr_b in zip(bucket_a, bucket_b):
                    np.testing.assert_array_equal(np.asarray(arr_a),
                                                  np.asarray(arr_b))

    def test_mixed_precision_retrain_is_two_dispatches(self):
        """bf16 leg + f32 polish = two fused dispatches; the splice
        rides the FIRST, never both."""
        rng = np.random.default_rng(7)
        users, items, vals = self._coo(rng, 1000)
        base = retrain.als_retrain(users, items, vals, 40, 25, rank=4,
                                   iterations=4, l2=0.05, seed=0,
                                   tol=0.0, plan_key="od2",
                                   bf16_sweeps=2)
        t = self._coo(rng, 80)
        u2 = np.concatenate([users, t[0]])
        i2 = np.concatenate([items, t[1]])
        v2 = np.concatenate([vals, t[2]])
        stats: dict = {}
        retrain.als_retrain(u2, i2, v2, 40, 25, rank=4, iterations=4,
                            l2=0.05, seed=0, prev_state=base, tol=0.0,
                            plan_key="od2", bf16_sweeps=2, stats=stats)
        assert stats["prep_plan"] == "reused"
        assert stats["train_dispatches"] == 2
        assert stats["one_dispatch"] is False

    def test_deferred_splice_bitwise_matches_eager_splice(self):
        """The in-dispatch `_splice_tree` scatters must produce trees
        bitwise-identical to apply_tail's eager `_set_entries`/
        `_clear_rows` path — including moved rows, cleared slots and
        appended delta buckets."""
        rng = np.random.default_rng(8)
        users, items, vals = self._coo(rng, 700, nu=30, ni=20)
        # tail with brand-new users → moved rows + delta buckets
        t_u = np.concatenate([rng.integers(0, 30, 50),
                              np.asarray([30, 31, 31])])
        t_i = np.concatenate([rng.integers(0, 20, 50),
                              np.asarray([3, 4, 19])])
        t_v = rng.normal(3, 1, 53).astype(np.float32)
        u2 = np.concatenate([users, t_u])
        i2 = np.concatenate([items, t_i])
        v2 = np.concatenate([vals, t_v])

        def trees_via(defer):
            retrain.drop_plans()
            retrain.prepare_with_reuse(users, items, vals, 30, 20,
                                       plan_key="bw")
            stats: dict = {}
            ut, it, _, _ = retrain.prepare_with_reuse(
                u2, i2, v2, 32, 20, plan_key="bw", stats=stats,
                defer_splice=defer)
            assert stats["prep_plan"] == "reused"
            if defer:
                sp = stats.get("pending_splices")
                assert sp is not None, "no deferred splice produced"
                ut = retrain._apply_splices(ut, sp[0])
                it = retrain._apply_splices(it, sp[1])
            return ut, it

        deferred, eager = trees_via(True), trees_via(False)
        for side_a, side_b in zip(deferred, eager):
            assert len(side_a) == len(side_b)
            for bucket_a, bucket_b in zip(side_a, side_b):
                for arr_a, arr_b in zip(bucket_a, bucket_b):
                    np.testing.assert_array_equal(np.asarray(arr_a),
                                                  np.asarray(arr_b))

    def test_jit_cache_stable_across_same_shape_retrains(self):
        """Same-size tails touching only resident rows → the spliced
        converge reuses its compiled program (the jit cache/dispatch
        pin of the acceptance criteria)."""
        rng = np.random.default_rng(9)
        # every user has degree 10 and every item degree 15 (both width
        # class 16 with headroom), and the tails below touch each
        # entity at most once per retrain — no width class ever moves,
        # so the splice pytree structure is identical across retrains
        users = np.repeat(np.arange(30, dtype=np.int64), 10)
        items = np.resize(np.arange(20, dtype=np.int64), len(users))
        vals = rng.normal(3, 1, len(users)).astype(np.float32)
        state = retrain.als_retrain(users, items, vals, 30, 20, rank=4,
                                    iterations=2, l2=0.05, seed=0,
                                    tol=0.0, plan_key="cache")

        def grow(u, i, v, seed):
            r = np.random.default_rng(seed)
            t_u = np.arange(8, dtype=np.int64)          # same 8 rows
            t_i = np.arange(8, dtype=np.int64) + 8     # same 8 items
            t_v = r.normal(3, 1, 8).astype(np.float32)
            return (np.concatenate([u, t_u]), np.concatenate([i, t_i]),
                    np.concatenate([v, t_v]))

        u2, i2, v2 = grow(users, items, vals, 1)
        s2: dict = {}
        state = retrain.als_retrain(u2, i2, v2, 30, 20, rank=4,
                                    iterations=2, l2=0.05, seed=0,
                                    prev_state=state, tol=0.0,
                                    plan_key="cache", stats=s2)
        assert s2["train_dispatches"] == 1
        cache_after_second = retrain._converge_spliced._cache_size()
        u3, i3, v3 = grow(u2, i2, v2, 2)
        s3: dict = {}
        retrain.als_retrain(u3, i3, v3, 30, 20, rank=4, iterations=2,
                            l2=0.05, seed=0, prev_state=state, tol=0.0,
                            plan_key="cache", stats=s3)
        assert s3["train_dispatches"] == 1
        assert retrain._converge_spliced._cache_size() \
            == cache_after_second, "same-shape retrain recompiled"

    def test_unfused_probe_path_applies_splice_eagerly(self, monkeypatch):
        monkeypatch.setenv("PIO_RETRAIN_FUSED", "0")
        rng = np.random.default_rng(10)
        users, items, vals = self._coo(rng, 900)
        base = retrain.als_retrain(users, items, vals, 40, 25, rank=4,
                                   iterations=4, l2=0.05, seed=0,
                                   tol=0.0, plan_key="uf")
        t = self._coo(rng, 70)
        u2 = np.concatenate([users, t[0]])
        i2 = np.concatenate([items, t[1]])
        v2 = np.concatenate([vals, t[2]])
        stats: dict = {}
        cont = retrain.als_retrain(u2, i2, v2, 40, 25, rank=4,
                                   iterations=4, l2=0.05, seed=0,
                                   prev_state=base, tol=0.0,
                                   plan_key="uf", stats=stats)
        assert stats["prep_plan"] == "reused"
        assert stats["train_dispatches"] > 1  # 2 splice + probe chunks
        assert np.all(np.isfinite(np.asarray(cont.user_factors)))


class TestFoldInFusedRouting:
    def test_ladder_buckets_match_dense_reference(self):
        from incubator_predictionio_tpu.speed.foldin import (
            FoldInSolver,
            dense_reference_solve,
        )

        rng = np.random.default_rng(12)
        other = rng.normal(0, 0.4, (60, 8)).astype(np.float32)
        solver = FoldInSolver(other, l2=0.05, reg_nnz=True,
                              use_kernel=True)
        assert solver.use_kernel
        rows = []
        for width in LADDER:
            d = width - 1 if width > 8 else width
            cols = rng.integers(0, 60, d).astype(np.int32)
            vals = rng.normal(3.5, 1.0, d).astype(np.float32)
            rows.append((cols, vals))
        out = solver.solve(rows)
        for k, (cols, vals) in enumerate(rows):
            ref = dense_reference_solve(other, cols, vals, 0.05)
            np.testing.assert_allclose(out[k], ref, atol=2e-4)

    def test_implicit_ladder_matches_dense_reference(self):
        from incubator_predictionio_tpu.speed.foldin import (
            FoldInSolver,
            dense_reference_solve,
        )

        rng = np.random.default_rng(13)
        other = rng.normal(0, 0.4, (50, 8)).astype(np.float32)
        solver = FoldInSolver(other, l2=0.05, implicit=True, alpha=2.0,
                              use_kernel=True)
        assert solver.use_kernel
        for width in (8, 32):
            cols = rng.integers(0, 50, width).astype(np.int32)
            vals = np.abs(rng.normal(1, 1, width)).astype(np.float32)
            out = solver.solve([(cols, vals)])
            ref = dense_reference_solve(other, cols, vals, 0.05,
                                        implicit=True, alpha=2.0)
            np.testing.assert_allclose(out[0], ref, atol=2e-4)

    def test_kernel_path_compile_cache_is_bounded(self):
        from incubator_predictionio_tpu.speed.foldin import (
            FoldInSolver,
            foldin_compile_cache_size,
        )

        rng = np.random.default_rng(14)
        other = rng.normal(0, 0.4, (40, 8)).astype(np.float32)
        solver = FoldInSolver(other, l2=0.1, use_kernel=True)
        solver.warmup()
        warm = foldin_compile_cache_size()
        for _ in range(3):
            d = int(rng.integers(1, 8))
            solver.solve([(rng.integers(0, 40, d).astype(np.int32),
                           rng.normal(3, 1, d).astype(np.float32))])
        assert foldin_compile_cache_size() == warm, (
            "steady-state fold-in recompiled on the kernel path")

    def test_over_budget_table_disables_kernel(self, monkeypatch):
        from incubator_predictionio_tpu.speed.foldin import FoldInSolver

        monkeypatch.setenv("PIO_ALS_FUSED_VMEM_MB", "0.000001")
        rng = np.random.default_rng(15)
        other = rng.normal(0, 0.4, (40, 8)).astype(np.float32)
        solver = FoldInSolver(other, l2=0.1, use_kernel=True)
        assert not solver.use_kernel  # budget overrides the forced flag
