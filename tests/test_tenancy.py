"""Multi-tenant serving platform (serving/tenancy.py + friends).

Covers the bounded tenant registry (PIO_TENANTS grammar, auth, the
metric-safe label gateway), the scheduler's tenant isolation planes
(weighted-fair dispatch, admission quotas, the contention slot caps),
the prediction server's access-key query path + tenant-scoped reload,
the per-tenant SLO specs, and the capacity report's per-tenant sizing
helpers — the PR-20 acceptance surface that is unit-testable without
the bench fleet (bench.py bench_tenants covers the end-to-end bars).
"""

import base64
import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from fake_engine import AP, make_engine, params
from incubator_predictionio_tpu.data.storage import Storage
from incubator_predictionio_tpu.obs import capacity, slo
from incubator_predictionio_tpu.serving import tenancy
from incubator_predictionio_tpu.serving.scheduler import (
    BatchScheduler,
    ShedError,
)
from incubator_predictionio_tpu.servers.prediction_server import (
    PredictionServer,
    ServerConfig,
)
from incubator_predictionio_tpu.workflow import CoreWorkflow


# -- registry parsing & bounds ----------------------------------------------

SPEC = ("alpha:alpha-key:weight=4;"
        "beta:beta-key:weight=1,quota=2;"
        "ghost:ghost-key:disabled=1")


def test_registry_parses_full_grammar():
    reg = tenancy.TenantRegistry.from_env(SPEC)
    assert reg.tenant_ids() == ("alpha", "beta", "ghost")
    a, b, g = reg.get("alpha"), reg.get("beta"), reg.get("ghost")
    assert a.weight == 4 and a.quota is None and a.enabled
    assert b.weight == 1 and b.quota == 2 and b.enabled
    assert not g.enabled
    assert reg.weights() == {"alpha": 4, "beta": 1, "ghost": 1}
    assert reg.quotas() == {"alpha": None, "beta": 2, "ghost": None}
    # keys never leak out of the shareable table
    assert "key" not in json.dumps(reg.describe())


def test_registry_empty_and_whitespace_entries():
    assert not tenancy.TenantRegistry.from_env("")
    assert not tenancy.TenantRegistry.from_env(" ; ;")
    assert len(tenancy.TenantRegistry.from_env(" a:k1 ; b:k2 ")) == 2


@pytest.mark.parametrize("bad", [
    "justanid",                        # no key
    "a:k:mystery=1",                   # unknown option
    "a:k1;a:k2",                       # duplicate tenant id
    "a:k;b:k",                         # duplicate access key
    "bad id!:k",                       # id grammar
    "a:k:weight=0",                    # weight must be >= 1
    "a:",                              # empty key
])
def test_registry_rejects_malformed_entries(bad):
    with pytest.raises(ValueError):
        tenancy.TenantRegistry.from_env(bad)


def test_registry_is_bounded():
    spec = ";".join(f"t{i}:k{i}" for i in range(tenancy.MAX_TENANTS + 1))
    with pytest.raises(ValueError, match="bounded"):
        tenancy.TenantRegistry.from_env(spec)
    # exactly at the bound is legal — the label cardinality ceiling
    spec = ";".join(f"t{i}:k{i}" for i in range(tenancy.MAX_TENANTS))
    assert len(tenancy.TenantRegistry.from_env(spec)) == \
        tenancy.MAX_TENANTS


def test_label_gateway_is_metric_safe():
    reg = tenancy.TenantRegistry.from_env(SPEC)
    assert reg.label("alpha") == "alpha"
    # wire values that never registered collapse to the bounded default
    assert reg.label("nope' OR 1=1") == tenancy.DEFAULT_TENANT
    assert reg.label(None) == tenancy.DEFAULT_TENANT
    assert tenancy.TenantRegistry().label("alpha") == \
        tenancy.DEFAULT_TENANT


# -- auth grammar (the event server's, serving edition) ---------------------

class _Req:
    def __init__(self, query=None, headers=None):
        self.query = query or {}
        self.headers = headers or {}


def test_extract_access_key_query_param_and_basic():
    assert tenancy.extract_access_key(
        _Req(query={"accessKey": "k1"})) == "k1"
    basic = base64.b64encode(b"k2:ignored-password").decode()
    assert tenancy.extract_access_key(
        _Req(headers={"authorization": f"Basic {basic}"})) == "k2"
    # query param wins over the header, same as the event server
    assert tenancy.extract_access_key(
        _Req(query={"accessKey": "k1"},
             headers={"authorization": f"Basic {basic}"})) == "k1"
    assert tenancy.extract_access_key(_Req()) is None
    assert tenancy.extract_access_key(
        _Req(headers={"authorization": "Basic %%%notb64"})) is None


def test_authenticate_maps_key_to_tenant_or_401():
    reg = tenancy.TenantRegistry.from_env(SPEC)
    assert reg.authenticate(_Req(query={"accessKey": "alpha-key"})) == \
        "alpha"
    for req in (_Req(),                                  # missing
                _Req(query={"accessKey": "wrong"}),      # unknown
                _Req(query={"accessKey": "ghost-key"})):  # disabled
        with pytest.raises(tenancy.TenantAuthError) as ei:
            reg.authenticate(req)
        assert ei.value.status == 401
    # empty registry = single-tenant compatibility mode: no auth at all
    assert tenancy.TenantRegistry().authenticate(_Req()) == \
        tenancy.DEFAULT_TENANT


def test_registry_singleton_follows_env(monkeypatch):
    tenancy.reset_registry()
    monkeypatch.setenv("PIO_TENANTS", "a:k1")
    assert tenancy.get_registry().tenant_ids() == ("a",)
    monkeypatch.setenv("PIO_TENANTS", "a:k1;b:k2")
    assert tenancy.get_registry().tenant_ids() == ("a", "b")
    monkeypatch.delenv("PIO_TENANTS")
    assert not tenancy.get_registry()
    tenancy.reset_registry()


# -- scheduler isolation planes ---------------------------------------------

def _drain(sched):
    sched.stop()


def test_scheduler_quota_sheds_only_the_quota_tenant():
    done = threading.Event()

    def handle(bodies, engine, tenant):
        done.wait(2.0)
        return list(bodies)

    s = BatchScheduler(handle, max_batch=8, workers=1, shed=False,
                       tenant_quotas={"beta": 2})
    try:
        futs = [s.submit(i, tenant="beta") for i in range(2)]
        # one batch may already be in flight; fill to the quota bound
        # (the shed lands on the FUTURE — admission stays non-raising)
        deadline = time.monotonic() + 2.0
        shed = None
        while time.monotonic() < deadline and shed is None:
            f = s.submit(99, tenant="beta")
            if f.done() and isinstance(f.exception(), ShedError):
                shed = f.exception()
            else:
                futs.append(f)
        assert shed is not None and shed.reason == "quota"
        assert shed.status == 503
        # an unquota'd tenant keeps being admitted through the flood
        ok = s.submit(1, tenant="alpha")
        assert not (ok.done() and ok.exception())
        futs.append(ok)
        done.set()
        for f in futs:
            f.result(timeout=5)
    finally:
        done.set()
        _drain(s)


def test_scheduler_slot_caps_weighted_by_contending_tenants():
    def handle(bodies, engine, tenant):
        return list(bodies)

    s = BatchScheduler(handle, max_batch=8, workers=2,
                       tenant_weights={"victim": 8, "aggressor": 1})
    try:
        with s._cv:
            now = s._clock()
            # one contender → no caps: a tenant alone on the scheduler
            # keeps every dispatcher thread (single-tenant throughput)
            s._t_last_submit = {"aggressor": now}
            assert s._slot_caps_locked(now) is None
            # two contenders → weighted shares of the 2-thread pool:
            # ceil(2·8/9)=2 for the victim (effectively uncapped),
            # ceil(2·1/9)=1 for the aggressor (one slot stays free)
            s._t_last_submit = {"aggressor": now, "victim": now}
            caps = s._slot_caps_locked(now)
            assert caps == {"victim": 2, "aggressor": 1}
            # stale contender ages out of the window
            s._t_last_submit["victim"] = \
                now - s.CONTEND_WINDOW_S - 1.0
            assert s._slot_caps_locked(now) is None
    finally:
        _drain(s)


def test_scheduler_single_worker_never_caps():
    def handle(bodies, engine, tenant):
        return list(bodies)

    s = BatchScheduler(handle, max_batch=8, workers=1,
                       tenant_weights={"a": 1, "b": 1})
    try:
        with s._cv:
            now = s._clock()
            s._t_last_submit = {"a": now, "b": now}
            assert s._slot_caps_locked(now) is None
    finally:
        _drain(s)


def test_scheduler_flooder_never_holds_every_dispatch_slot():
    """The isolation invariant itself: under a closed-loop flood from a
    low-weight tenant, a contending light tenant means the flooder's
    concurrent in-flight dispatches stay under its weighted slot cap —
    one dispatcher thread is always free for the light tenant."""
    floor_s = 0.02

    def handle(bodies, engine, tenant):
        time.sleep(floor_s)
        return list(bodies)

    s = BatchScheduler(handle, max_batch=4, workers=2, shed=False,
                       tenant_weights={"victim": 8, "aggressor": 1})
    stop = threading.Event()

    def flood():
        while not stop.is_set():
            try:
                s.submit({"q": 1}, tenant="aggressor").result(timeout=5)
            except Exception:
                return

    threads = [threading.Thread(target=flood, daemon=True)
               for _ in range(6)]
    try:
        for t in threads:
            t.start()
        max_agg_inflight = 0
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            # victim keeps contending (and must never be starved)
            s.submit({"q": 1}, tenant="victim").result(timeout=5)
            with s._cv:
                max_agg_inflight = max(
                    max_agg_inflight,
                    s._tenant_inflight_locked("aggressor"))
        assert max_agg_inflight <= 1, (
            "aggressor held every dispatch slot despite a contending "
            "light tenant")
    finally:
        stop.set()
        _drain(s)
        for t in threads:
            t.join(timeout=5)


# -- prediction server: access-key query path + tenant-scoped reload --------

@pytest.fixture
def tenant_server(monkeypatch):
    monkeypatch.setenv(
        "PIO_TENANTS",
        "alpha:alpha-key:weight=4;beta:beta-key:quota=8;"
        "ghost:ghost-key:disabled=1")
    tenancy.reset_registry()
    Storage.configure({
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    })
    engine = make_engine()
    CoreWorkflow.run_train(engine, params(ds=9, algos=[("algo0", AP(1))]),
                           engine_variant="tenants")
    ps = PredictionServer(engine, ServerConfig(
        ip="127.0.0.1", port=0, engine_variant="tenants",
        server_key="sekrit"))
    port = ps.start_background()
    yield ps, port
    ps.stop()
    Storage.reset()
    tenancy.reset_registry()


def _post(port, path, body):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, json.loads(resp.read() or b"null")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"null")


def test_query_path_requires_access_key(tenant_server):
    _ps, port = tenant_server
    status, body = _post(port, "/queries.json", {"qx": 1})
    assert status == 401 and "accessKey" in body["message"]
    status, _ = _post(port, "/queries.json?accessKey=wrong", {"qx": 1})
    assert status == 401
    status, _ = _post(port, "/queries.json?accessKey=ghost-key", {"qx": 1})
    assert status == 401
    status, body = _post(port, "/queries.json?accessKey=alpha-key",
                         {"qx": 7})
    assert status == 200 and body["qx"] == 7


def test_status_renders_per_tenant_block(tenant_server):
    _ps, port = tenant_server
    _post(port, "/queries.json?accessKey=alpha-key", {"qx": 1})
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/",
                                timeout=30) as resp:
        info = json.loads(resp.read())
    blocks = info["tenants"]
    assert set(blocks) == {"alpha", "beta", "ghost"}
    assert blocks["alpha"]["weight"] == 4
    assert blocks["beta"]["quota"] == 8
    assert blocks["ghost"]["enabled"] is False
    # no tenant pinned a variant: all share the default deploy
    assert blocks["alpha"]["sharedDeploy"] is True
    # keys stay out of the shareable status page
    assert "alpha-key" not in json.dumps(info)


def test_tenant_scoped_reload_leaves_default_deploy_alone(tenant_server):
    ps, port = tenant_server
    default_instance = ps.engine_instance.id
    status, body = _post(
        port, "/reload?accessKey=sekrit&tenant=alpha", {})
    assert status == 200 and "alpha" in body["message"]
    # the tenant deploy landed; the default deploy never swapped
    assert "alpha" in ps._deploys
    assert ps.engine_instance.id == default_instance
    # the co-resident deploy serves queries for its tenant
    status, body = _post(port, "/queries.json?accessKey=alpha-key",
                         {"qx": 3})
    assert status == 200 and body["qx"] == 3
    # unknown tenants 404 instead of clobbering anything
    status, _ = _post(port, "/reload?accessKey=sekrit&tenant=nope", {})
    assert status == 404
    # and the reload seam still honors the server key
    status, _ = _post(port, "/reload?accessKey=wrong&tenant=alpha", {})
    assert status == 401


# -- per-tenant SLO specs ---------------------------------------------------

def test_tenant_specs_slice_the_latency_family(monkeypatch):
    monkeypatch.setenv("PIO_TENANTS", "alpha:k1;beta:k2")
    tenancy.reset_registry()
    try:
        specs = slo.tenant_specs()
        assert [s.name for s in specs] == \
            ["serve_p99@alpha", "serve_p99@beta"]
        for s in specs:
            assert s.metric == "pio_query_latency_seconds"
            assert s.labels == (("tenant", s.name.split("@")[1]),)
        # the fleet objectives keep their unlabeled (all-tenant) read
        names = [s.name for s in slo.default_specs()]
        assert "serve_p99" in names and "serve_p99@alpha" in names
        monkeypatch.delenv("PIO_TENANTS")
        tenancy.reset_registry()
        assert slo.tenant_specs() == ()
    finally:
        tenancy.reset_registry()


# -- capacity: per-tenant sizing --------------------------------------------

def test_parse_tenant_demands_drops_malformed():
    assert capacity.parse_tenant_demands(
        "a=100; b=2000 ;typo;c=;d=-5;e=abc") == {"a": 100.0, "b": 2000.0}
    assert capacity.parse_tenant_demands("") == {}


def test_bin_pack_tenants_first_fit_with_chunk_split():
    pack = capacity.bin_pack_tenants({"b": 2000, "a": 100}, 800.0)
    # b splits into 800+800+400; a's 100 first-fits into b's third
    # worker (400+100 <= 800) — co-residency, not a fourth worker
    assert pack["workers"] == 3
    assert pack["assignment"]["b"] == [0, 1, 2]
    assert pack["assignment"]["a"] == [2]
    assert capacity.bin_pack_tenants({}, 800.0)["workers"] == 0
    assert capacity.bin_pack_tenants({"a": 10}, 0.0)["workers"] == 0
