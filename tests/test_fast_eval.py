"""FastEvalEngine prefix-cache behavior (parity: FastEvalEngineTest.scala)."""

from fake_engine import (
    AP,
    DSP,
    PP,
    SP,
    Algorithm0,
    Algorithm1,
    DataSource0,
    Preparator0,
    Serving0,
)
from incubator_predictionio_tpu.core import EngineParams
from incubator_predictionio_tpu.core.fast_eval import (
    FastEvalEngine,
    FastEvalEngineWorkflow,
)
from incubator_predictionio_tpu.parallel.context import RuntimeContext

CALLS = {"read": 0, "prepare": 0, "train": 0}


class CountingDataSource(DataSource0):
    def read_eval(self, ctx):
        CALLS["read"] += 1
        return super().read_eval(ctx)


class CountingPreparator(Preparator0):
    def prepare(self, ctx, td):
        CALLS["prepare"] += 1
        return super().prepare(ctx, td)


class CountingAlgorithm(Algorithm0):
    def train(self, ctx, pd):
        CALLS["train"] += 1
        return super().train(ctx, pd)


def make_fast():
    return FastEvalEngine(
        CountingDataSource,
        CountingPreparator,
        {"algo": CountingAlgorithm, "algo1": Algorithm1},
        Serving0,
    )


def ep(ds=1, pp=2, ap=3, sp=4):
    return EngineParams(
        data_source_params=("", DSP(ds)),
        preparator_params=("", PP(pp)),
        algorithm_params_list=[("algo", AP(ap))],
        serving_params=("", SP(sp)),
    )


def reset():
    CALLS.update(read=0, prepare=0, train=0)


def test_serving_only_variation_reuses_everything():
    reset()
    engine = make_fast()
    out = engine.batch_eval(RuntimeContext(), [ep(sp=1), ep(sp=2), ep(sp=3)])
    assert len(out) == 3
    assert CALLS["read"] == 1       # one data source prefix
    assert CALLS["prepare"] == 2    # one per eval set (2 sets), computed once
    assert CALLS["train"] == 2      # one per eval set, computed once


def test_algo_variation_reuses_prepared_data():
    reset()
    engine = make_fast()
    engine.batch_eval(RuntimeContext(), [ep(ap=1), ep(ap=2)])
    assert CALLS["read"] == 1
    assert CALLS["prepare"] == 2    # cached across algo variants
    assert CALLS["train"] == 4      # 2 algo variants × 2 eval sets


def test_data_source_variation_recomputes():
    reset()
    engine = make_fast()
    engine.batch_eval(RuntimeContext(), [ep(ds=1), ep(ds=2)])
    assert CALLS["read"] == 2
    assert CALLS["prepare"] == 4
    assert CALLS["train"] == 4


def test_results_match_plain_engine():
    reset()
    from incubator_predictionio_tpu.core import Engine

    plain = Engine(
        CountingDataSource, CountingPreparator,
        {"algo": CountingAlgorithm, "algo1": Algorithm1}, Serving0,
    )
    candidates = [ep(ap=1), ep(ap=2), ep(sp=9)]
    fast_out = make_fast().batch_eval(RuntimeContext(), candidates)
    plain_out = plain.batch_eval(RuntimeContext(), candidates)
    assert [
        [(info, qpas) for info, qpas in data] for _p, data in fast_out
    ] == [[(info, qpas) for info, qpas in data] for _p, data in plain_out]
