"""Implicit ALS, NaiveBayes, LogReg ops + the e2 library."""

import numpy as np
import pytest

import jax.numpy as jnp

from incubator_predictionio_tpu.e2 import (
    BinaryVectorizer,
    CategoricalNaiveBayes,
    LabeledPoint,
    MarkovChain,
    split_data,
)
from incubator_predictionio_tpu.ops.als import als_train_implicit
from incubator_predictionio_tpu.ops.logreg import (
    logreg_accuracy,
    logreg_fit,
    logreg_predict,
)
from incubator_predictionio_tpu.ops.nb import nb_accuracy, nb_fit, nb_predict


def test_implicit_als_separates_blocks():
    # two user/item blocks with implicit view counts
    rng = np.random.default_rng(0)
    users, items, weights = [], [], []
    for u in range(20):
        block = u % 2
        for i in range(10):
            if rng.random() < 0.6:
                users.append(u)
                items.append(block * 10 + i)
                weights.append(float(rng.integers(1, 5)))
    state = als_train_implicit(
        np.array(users), np.array(items), np.array(weights, np.float32),
        n_users=20, n_items=20, rank=8, iterations=8, l2=0.1, alpha=2.0,
    )
    uf = np.asarray(state.user_factors)
    itf = np.asarray(state.item_factors)
    # user 0 (block 0) scores block-0 items higher than block-1 items
    scores = itf @ uf[0]
    assert scores[:10].mean() > scores[10:].mean() + 0.1
    scores1 = itf @ uf[1]
    assert scores1[10:].mean() > scores1[:10].mean() + 0.1


def test_nb_fit_predict():
    rng = np.random.default_rng(1)
    # class 0 concentrates on features 0-1; class 1 on features 2-3
    n = 200
    labels = rng.integers(0, 2, n)
    feats = np.zeros((n, 4), np.float32)
    for i, y in enumerate(labels):
        base = 0 if y == 0 else 2
        feats[i, base] = rng.integers(3, 8)
        feats[i, base + 1] = rng.integers(1, 5)
        feats[i, rng.integers(0, 4)] += 1  # noise
    model = nb_fit(jnp.asarray(feats), jnp.asarray(labels, jnp.int32), 2)
    assert nb_accuracy(model, feats, labels) > 0.95
    single = nb_predict(model, jnp.asarray(feats[:1]))
    assert int(single[0]) == labels[0]


def test_logreg_fit_predict():
    rng = np.random.default_rng(2)
    n = 300
    x = rng.normal(size=(n, 3)).astype(np.float32)
    w_true = np.array([[2.0, -1.0], [-2.0, 1.5], [0.5, 0.5]], np.float32)
    logits = x @ w_true
    y = logits.argmax(axis=1)
    model = logreg_fit(jnp.asarray(x), jnp.asarray(y, jnp.int32),
                       n_classes=2, steps=200)
    assert logreg_accuracy(model, x, y) > 0.95


def test_categorical_naive_bayes():
    points = [
        LabeledPoint("spam", ("viagra", "now")),
        LabeledPoint("spam", ("viagra", "later")),
        LabeledPoint("ham", ("hello", "now")),
        LabeledPoint("ham", ("hello", "later")),
        LabeledPoint("ham", ("meeting", "now")),
    ]
    model = CategoricalNaiveBayes.train(points)
    assert model.predict(("viagra", "now")) == "spam"
    assert model.predict(("hello", "later")) == "ham"
    # unseen value with default -inf → score -inf
    score = model.log_score(LabeledPoint("spam", ("unseen", "now")))
    assert score == float("-inf")
    # custom default (min of seen)
    score2 = model.log_score(
        LabeledPoint("spam", ("unseen", "now")),
        default_likelihood=lambda ls: min(ls) if ls else float("-inf"),
    )
    assert np.isfinite(score2)
    assert model.log_score(LabeledPoint("nope", ("a", "b"))) is None
    with pytest.raises(ValueError):
        model.log_score(LabeledPoint("spam", ("only-one",)))


def test_markov_chain():
    # transitions: 0 -> 1 (3x), 0 -> 2 (1x), 1 -> 0 (2x)
    model = MarkovChain.train(
        rows=[0, 0, 1], cols=[1, 2, 0], counts=[3, 1, 2], top_n=2
    )
    assert model.predict([0, 1]) == [1, 0]
    assert model.predict([9]) == [-1]  # unknown state
    top = model.top_n(0)
    assert top[0] == (1, 0.75)
    assert top[1] == (2, 0.25)


def test_binary_vectorizer():
    vec = BinaryVectorizer.fit([("color", "red"), ("color", "blue"),
                                ("size", "L")])
    assert vec.n == 3
    v = vec.transform({"color": "red", "size": "L"})
    assert v.sum() == 2.0
    assert vec.transform({"color": "green"}).sum() == 0.0  # unseen ignored
    batch = vec.transform_batch([{"color": "blue"}, {}])
    assert batch.shape == (2, 3)
    assert batch[1].sum() == 0


def test_split_data():
    data = list(range(10))
    folds = split_data(3, data, lambda d: (f"q{d}", f"a{d}"))
    assert len(folds) == 3
    train0, idx0, qa0 = folds[0]
    assert idx0 == 0
    assert 0 not in train0 and 3 not in train0
    assert ("q0", "a0") in qa0
    # every element appears in exactly one test fold
    all_test = [q for _t, _i, qa in folds for q, _a in qa]
    assert len(all_test) == 10
    with pytest.raises(ValueError):
        split_data(1, data, lambda d: (d, d))
