"""Attention kernels + sequence parallelism, on the 8-device CPU mesh.

Mirrors the reference's local[4]-threads simulation of its cluster
(core/src/test/.../workflow/BaseTest.scala:71-88): distributed numerics are
validated against the dense single-device implementation.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from incubator_predictionio_tpu.ops.attention import (
    blockwise_attention,
    dot_product_attention,
)
from incubator_predictionio_tpu.parallel.mesh import SEQ_AXIS, make_mesh
from incubator_predictionio_tpu.parallel.ring import (
    ring_attention,
    ulysses_attention,
)
from jax.sharding import Mesh


def _qkv(b=2, s=64, h=4, d=16, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    shape = (b, s, h, d)
    return tuple(jax.random.normal(k, shape, jnp.float32) for k in ks)


def _seq_mesh(n=8):
    return Mesh(np.array(jax.devices()[:n]), (SEQ_AXIS,))


@pytest.mark.parametrize("causal", [True, False])
def test_blockwise_matches_dense(causal):
    q, k, v = _qkv()
    dense = dot_product_attention(q, k, v, causal=causal)
    blocked = blockwise_attention(q, k, v, causal=causal, block_size=16)
    np.testing.assert_allclose(dense, blocked, atol=1e-5)


def test_blockwise_ragged_block_padding():
    q, k, v = _qkv(s=56)  # not a multiple of block_size
    dense = dot_product_attention(q, k, v, causal=False)
    blocked = blockwise_attention(q, k, v, causal=False, block_size=16)
    np.testing.assert_allclose(dense, blocked, atol=1e-5)


def test_dense_offsets_mask_cross_block():
    # the global-position masking rule ring attention relies on:
    q, k, v = _qkv(s=32)
    # q block strictly after the kv block → every key visible = non-causal
    past = dot_product_attention(q, k, v, causal=True, q_offset=64,
                                 kv_offset=0)
    np.testing.assert_allclose(
        past, dot_product_attention(q, k, v, causal=False), atol=1e-5
    )
    # kv block strictly in the future → fully masked rows produce 0, not NaN
    future = dot_product_attention(q, k, v, causal=True, q_offset=0,
                                   kv_offset=96)
    np.testing.assert_allclose(future, jnp.zeros_like(q), atol=1e-6)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_dense(causal):
    q, k, v = _qkv(s=64)
    mesh = _seq_mesh(8)
    out = ring_attention(q, k, v, mesh, causal=causal)
    dense = dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), dense, atol=1e-5)


def test_ring_attention_sharded_inputs_jit():
    from jax.sharding import NamedSharding, PartitionSpec as P

    q, k, v = _qkv(s=64)
    mesh = _seq_mesh(8)
    shard = NamedSharding(mesh, P(None, SEQ_AXIS))
    qs, ks, vs = (jax.device_put(x, shard) for x in (q, k, v))
    out = jax.jit(
        lambda a, b, c: ring_attention(a, b, c, mesh, causal=True)
    )(qs, ks, vs)
    dense = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), dense, atol=1e-5)
    assert tuple(out.sharding.spec)[:2] == (None, SEQ_AXIS)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_matches_dense(causal):
    q, k, v = _qkv(s=64, h=8)
    mesh = _seq_mesh(8)
    out = ulysses_attention(q, k, v, mesh, causal=causal)
    dense = dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), dense, atol=1e-5)


def test_ulysses_rejects_indivisible_heads():
    q, k, v = _qkv(s=64, h=4)
    mesh = _seq_mesh(8)
    with pytest.raises(ValueError):
        ulysses_attention(q, k, v, mesh)


def test_ring_attention_grads_flow():
    q, k, v = _qkv(s=32, h=2, d=8)
    mesh = _seq_mesh(8)

    def loss_ring(q, k, v):
        return ring_attention(q, k, v, mesh, causal=True).sum()

    def loss_dense(q, k, v):
        return dot_product_attention(q, k, v, causal=True).sum()

    g_ring = jax.grad(loss_ring)(q, k, v)
    g_dense = jax.grad(loss_dense)(q, k, v)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_dense),
                               atol=1e-4)
