"""PredictionServer contract: deploy, query, feedback loop, reload, stop.

Parity: CreateServer.scala behavior incl. the feedback loop posting predict
events back to a live EventServer.
"""

import json
import time
import urllib.error
import urllib.request

import pytest

from fake_engine import AP, make_engine, params
from incubator_predictionio_tpu.data.storage import AccessKey, App, Storage
from incubator_predictionio_tpu.servers.event_server import (
    EventServer,
    EventServerConfig,
)
from incubator_predictionio_tpu.servers.plugins import EngineServerPlugin, PluginContext
from incubator_predictionio_tpu.servers.prediction_server import (
    PredictionServer,
    ServerConfig,
    undeploy,
)
from incubator_predictionio_tpu.workflow import CoreWorkflow


def call(port, method, path, body=None):
    url = f"http://127.0.0.1:{port}{path}"
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"} if data else {},
    )
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, json.loads(resp.read() or b"null")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"null")


class RewritingBlocker(EngineServerPlugin):
    output_blocker = True

    def process(self, variant, query, prediction, context):
        if isinstance(prediction, dict):
            prediction = dict(prediction, blocked_by="RewritingBlocker")
        return prediction


@pytest.fixture
def stack():
    """memory storage + trained engine + event server + prediction server."""
    Storage.configure({
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    })
    app_id = Storage.get_meta_data_apps().insert(App(0, "ps-app"))
    Storage.get_meta_data_access_keys().insert(AccessKey("fbkey", app_id))

    engine = make_engine()
    CoreWorkflow.run_train(engine, params(ds=9, algos=[("algo0", AP(1))]),
                           engine_variant="served")

    es = EventServer(EventServerConfig(ip="127.0.0.1", port=0))
    es_port = es.start_background()

    ps = PredictionServer(
        engine,
        ServerConfig(
            ip="127.0.0.1", port=0, engine_variant="served",
            event_server_ip="127.0.0.1", event_server_port=es_port,
            access_key="fbkey", feedback=True, server_key="sekrit",
        ),
        PluginContext([RewritingBlocker()]),
    )
    ps_port = ps.start_background()
    yield ps, ps_port, es, es_port
    ps.stop()
    es.stop()
    Storage.reset()


def test_status_page(stack):
    ps, port, _es, _esp = stack
    status, body = call(port, "GET", "/")
    assert status == 200
    assert body["status"] == "alive"
    assert body["engineVariant"] == "served"
    assert body["algorithms"] == ["Algorithm0"]
    assert body["requestCount"] == 0


def test_query_pipeline_and_bookkeeping(stack):
    ps, port, _es, _esp = stack
    status, body = call(port, "POST", "/queries.json", {"qx": 5})
    assert status == 200
    # Prediction(model=Model(ds_id=9, pp_id=2, ap_id=1), qx=5)
    assert body["qx"] == 5
    assert body["model"]["ds_id"] == 9
    assert body["blocked_by"] == "RewritingBlocker"  # output blocker ran
    status, info = call(port, "GET", "/")
    assert info["requestCount"] == 1
    assert info["lastServingSec"] > 0


def test_query_malformed_400(stack):
    ps, port, _es, _esp = stack
    status, body = call(port, "POST", "/queries.json", {"bogus": True})
    assert status == 400


def test_feedback_event_reaches_event_server(stack):
    ps, port, _es, es_port = stack
    call(port, "POST", "/queries.json", {"qx": 7})
    deadline = time.time() + 5
    found = []
    while time.time() < deadline and not found:
        status, got = call(
            es_port, "GET",
            "/events.json?accessKey=fbkey&event=predict",
        )
        if status == 200:
            found = got
        else:
            time.sleep(0.05)
    assert found, "feedback predict event never arrived"
    ev = found[0]
    assert ev["entityType"] == "pio_pr"
    assert ev["properties"]["query"] == {"qx": 7}
    assert ev["properties"]["engineInstanceId"]


def test_reload_picks_up_new_instance(stack):
    ps, port, _es, _esp = stack
    # train a new instance with different params
    CoreWorkflow.run_train(ps.engine, params(ds=42, algos=[("algo0", AP(2))]),
                           engine_variant="served")
    # unauthorized reload
    assert call(port, "POST", "/reload")[0] == 401
    status, _ = call(port, "POST", "/reload?accessKey=sekrit")
    assert status == 200
    status, body = call(port, "POST", "/queries.json", {"qx": 1})
    assert body["model"]["ds_id"] == 42
    assert body["model"]["ap_id"] == 2


def test_stop_authed_and_shuts_down(stack):
    ps, port, _es, _esp = stack
    assert call(port, "POST", "/stop")[0] == 401
    status, _ = call(port, "POST", "/stop?accessKey=sekrit")
    assert status == 200
    deadline = time.time() + 5
    down = False
    while time.time() < deadline and not down:
        try:
            call(port, "GET", "/")
            time.sleep(0.05)
        except Exception:
            down = True
    assert down
    assert not undeploy("127.0.0.1", port)  # already down


def test_stop_timer_is_daemonized(stack, monkeypatch):
    """Lifecycle regression (pio-lint thread-lifecycle): the /stop
    route's deferred-shutdown Timer must be a daemon — if the process
    is torn down some other way first, a pending non-daemon timer
    would block interpreter exit."""
    import threading

    captured = []

    class FakeTimer:
        def __init__(self, interval, function, *a, **kw):
            self.interval = interval
            self.function = function
            self.daemon = False
            self.started = False
            captured.append(self)

        def start(self):
            self.started = True

        def cancel(self):
            pass

    monkeypatch.setattr(threading, "Timer", FakeTimer)
    ps, port, _es, _esp = stack
    status, _ = call(port, "POST", "/stop?accessKey=sekrit")
    assert status == 200
    assert len(captured) == 1
    timer = captured[0]
    assert timer.started
    assert timer.daemon is True
    assert timer.function == ps.stop
    # the fake never fired, so the server is still up for teardown
    assert call(port, "GET", "/")[0] == 200


def test_plugins_listing(stack):
    ps, port, _es, _esp = stack
    status, body = call(port, "GET", "/plugins.json")
    assert status == 200
    assert "RewritingBlocker" in body["plugins"]["outputblockers"]


def test_concurrent_queries_micro_batch(stack):
    """Concurrent queries fuse into micro-batches (one batch_predict per
    drain) and every client still gets ITS OWN result — no cross-wiring.
    The reference serves queries strictly one-at-a-time
    (CreateServer.scala:523 'TODO: Parallelize')."""
    import threading

    ps, port, _es, _esp = stack
    n_clients, per_client = 16, 4
    errors = []

    def client(cid):
        for j in range(per_client):
            qx = cid * 1000 + j
            status, body = call(port, "POST", "/queries.json", {"qx": qx})
            if status != 200 or body.get("qx") != qx:
                errors.append((cid, j, status, body))

    threads = [threading.Thread(target=client, args=(c,))
               for c in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    status, info = call(port, "GET", "/")
    assert info["requestCount"] == n_clients * per_client
    # under 16-way concurrency at least one drain must have fused >1 query
    assert info["maxBatchServed"] > 1


def test_batch_isolates_bad_queries(stack):
    """A malformed query inside a fused batch 400s alone; batchmates
    succeed."""
    import threading

    ps, port, _es, _esp = stack
    results = {}

    def good(i):
        results[i] = call(port, "POST", "/queries.json", {"qx": i})

    def bad():
        results["bad"] = call(port, "POST", "/queries.json", {"bogus": 1})

    threads = [threading.Thread(target=good, args=(i,)) for i in range(8)]
    threads.append(threading.Thread(target=bad))
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert results["bad"][0] == 400
    for i in range(8):
        assert results[i][0] == 200 and results[i][1]["qx"] == i


# ---------------------------------------------------------------------------
# deploy lifecycle hardening (CreateServer.scala:283-308, :371-381, :449-460)
# ---------------------------------------------------------------------------

def _mini_server(port=0):
    """A dumb HTTP listener standing in for 'something on the port'."""
    from incubator_predictionio_tpu.utils.http import (
        HttpServer,
        Request,
        Response,
        Router,
    )

    r = Router()
    hits = []

    @r.post("/stop")
    def stop(request: Request) -> Response:
        hits.append("stop")
        return Response(404, {"message": "not a pio server"})

    srv = HttpServer(r, "127.0.0.1", port)
    return srv, hits


def test_bind_retry_on_occupied_port():
    """Bind retries on EADDRINUSE: a port freed within the retry window
    binds (MasterActor Http.CommandFailed handling,
    CreateServer.scala:371-381). Tested directly at the HttpServer level
    so the first bind attempt genuinely collides (the prediction server's
    undeploy handshake would otherwise consume time and free the port
    before the first bind)."""
    import socket
    import threading

    from incubator_predictionio_tpu.utils.http import HttpServer, Router

    sock = socket.socket()
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    sock.bind(("127.0.0.1", 0))
    sock.listen(1)
    port = sock.getsockname()[1]

    srv = HttpServer(Router(), "127.0.0.1", port,
                     bind_retries=3, bind_retry_delay=0.4)
    # free the port ~0.6s in: after the first bind failure, within retries
    threading.Timer(0.6, sock.close).start()
    try:
        bound = srv.start_background()
        assert bound == port
    finally:
        srv.stop()


def test_bind_no_retry_on_non_transient_oserror():
    """Non-EADDRINUSE OSErrors (bad host) fail fast, no retry loop."""
    import time as _time

    from incubator_predictionio_tpu.utils.http import HttpServer, Router

    srv = HttpServer(Router(), "256.256.256.256", 1,
                     bind_retries=3, bind_retry_delay=1.0)
    t0 = _time.monotonic()
    with pytest.raises(RuntimeError, match="failed to start"):
        srv.start_background()
    assert _time.monotonic() - t0 < 2.5  # did not burn 3x1s retries


def test_bind_fails_after_retries_exhausted(stack):
    import socket

    from fake_engine import make_engine

    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    sock.listen(1)
    port = sock.getsockname()[1]
    try:
        ps2 = PredictionServer(make_engine(), ServerConfig(
            ip="127.0.0.1", port=port, engine_variant="served"))
        ps2.http.bind_retries = 1
        ps2.http.bind_retry_delay = 0.1
        with pytest.raises(RuntimeError, match="failed to start"):
            ps2.start_background()
    finally:
        sock.close()


def test_undeploy_before_deploy_replaces_stale_server(stack):
    """Deploying onto an address with a live engine server stops the old
    one first (undeploy-before-deploy, CreateServer.scala:283-308)."""
    from fake_engine import make_engine

    ps, port, _es, _esp = stack
    assert call(port, "GET", "/")[0] == 200
    # a second deploy on the SAME port: the stale server must be asked to
    # stop (server-key authed), then the port reused
    ps2 = PredictionServer(make_engine(), ServerConfig(
        ip="127.0.0.1", port=port, engine_variant="served",
        server_key="sekrit"))
    try:
        bound = ps2.start_background()
        assert bound == port
        status, body = call(port, "GET", "/")
        assert status == 200 and body["requestCount"] == 0
    finally:
        ps2.stop()


def test_undeploy_foreign_process_logs_and_continues(stack, caplog):
    """A non-pio process answering /stop with an error is reported, not
    crashed into (MasterActor.undeploy 404 branch)."""
    import logging

    from fake_engine import make_engine

    srv, hits = _mini_server()
    port = srv.start_background()
    ps2 = PredictionServer(make_engine(), ServerConfig(
        ip="127.0.0.1", port=port, engine_variant="served"))
    ps2.http.bind_retries = 0
    with caplog.at_level(logging.ERROR):
        with pytest.raises(RuntimeError):
            ps2.start_background()  # foreign owner keeps the port
    assert hits == ["stop"]
    assert any("Another process is using" in r.message for r in caplog.records)
    srv.stop()


def test_log_url_ships_query_errors(stack):
    """Query errors POST to --log-url with the prefix + engine instance
    (remoteLog, CreateServer.scala:449-460)."""
    import threading

    from incubator_predictionio_tpu.utils.http import (
        HttpServer,
        Request,
        Response,
        Router,
    )

    ps, port, _es, _esp = stack
    received = []
    got_one = threading.Event()
    r = Router()

    @r.post("/collect")
    def collect(request: Request) -> Response:
        received.append(request.body.decode())
        got_one.set()
        return Response(200, {})

    collector = HttpServer(r, "127.0.0.1", 0)
    cport = collector.start_background()
    ps.config.log_url = f"http://127.0.0.1:{cport}/collect"
    ps.config.log_prefix = "PIOLOG "
    try:
        status, _ = call(port, "POST", "/queries.json", {"bogus": 1})
        assert status == 400
        assert got_one.wait(10), "no remote log arrived"
        assert received[0].startswith("PIOLOG ")
        doc = json.loads(received[0][len("PIOLOG "):])
        assert doc["engineInstance"]["id"]
        assert "Stack Trace" in doc["message"]
    finally:
        ps.config.log_url = None
        collector.stop()


def test_warmup_hook_runs_after_bind(stack, caplog):
    """start_background spawns the warmup thread; the fake engine's algo
    has the default no-op warmup, so the pass completes and logs. A
    failing warmup must be swallowed (queries compile on demand)."""
    import logging
    import time

    ps, port, _es, _esp = stack
    # the fixture's own warmup ran during setup; re-trigger under caplog
    # to observe the completion log deterministically
    with caplog.at_level(logging.INFO):
        ps._warmup_async()
        for _ in range(200):
            if any("serving warmup done" in r.message
                   for r in caplog.records):
                break
            time.sleep(0.05)
    assert any("serving warmup done" in r.message for r in caplog.records)

    # a warmup that raises is logged, not fatal: queries still serve
    class Exploding:
        def warmup(self, model, max_batch=1):
            raise RuntimeError("boom")

    ps.algorithms = [Exploding()]
    with caplog.at_level(logging.ERROR):
        ps._warmup_async()
        for _ in range(100):
            if any("warmup failed" in r.message for r in caplog.records):
                break
            time.sleep(0.05)
    assert any("warmup failed" in r.message for r in caplog.records)


# ---------------------------------------------------------------------------
# batch_serve_json columnar fast path (core/base.py batch_serve_json;
# models/recommendation/engine.py ALSAlgorithm.batch_serve_json)
# ---------------------------------------------------------------------------

def _als_fixture():
    import jax.numpy as jnp
    import numpy as np

    from incubator_predictionio_tpu.data.bimap import BiMap
    from incubator_predictionio_tpu.models.recommendation.engine import (
        ALSAlgorithm,
        ALSAlgorithmParams,
        ALSModel,
    )

    rng = np.random.default_rng(3)
    nu, ni, k = 40, 25, 8
    model = ALSModel(
        user_factors=jnp.asarray(rng.normal(size=(nu, k)).astype(np.float32)),
        item_factors=jnp.asarray(rng.normal(size=(ni, k)).astype(np.float32)),
        user_bimap=BiMap({f"u{i}": i for i in range(nu)}),
        item_bimap=BiMap({f"i{i}": i for i in range(ni)}),
        item_years={"i3": 1999, "i7": 2004},
        item_categories={},
    )
    return ALSAlgorithm(ALSAlgorithmParams(rank=k)), model


def test_batch_serve_json_byte_identical_to_object_path():
    """The rendered fast-path bytes must be exactly what the object path
    would put on the wire FOR THE SAME BATCH: batch_predict → serve →
    json.dumps(to_jsonable(...)). (Compared against the batched object
    path, not per-query predict: the batched matmul's f32 rounding is the
    wire truth for any batch the micro-batcher forms.)"""
    from incubator_predictionio_tpu.models.recommendation.engine import Query
    from incubator_predictionio_tpu.utils import json_codec

    algo, model = _als_fixture()
    docs = [
        {"user": "u1", "num": 5},
        {"user": "u2", "num": 10},
        {"user": "u39", "num": 3},
    ]
    fast = algo.batch_serve_json(model, docs)
    assert all(isinstance(b, bytes) for b in fast)
    objs = dict(algo.batch_predict(model, [
        (i, Query(user=d["user"], num=d["num"]))
        for i, d in enumerate(docs)]))
    for i, (d, payload) in enumerate(zip(docs, fast)):
        expect = json.dumps(json_codec.to_jsonable(objs[i])).encode()
        assert payload == expect, (d, payload, expect)


def test_batch_serve_json_rejects_non_plain_docs():
    """Anything beyond the exact plain shape falls to the object path."""
    algo, model = _als_fixture()
    docs = [
        {"user": "u1", "num": 5, "creationYear": 2000},  # extra key
        {"user": "nosuch", "num": 5},                    # unknown user
        {"user": "u1"},                                   # missing num
        {"user": "u1", "num": True},                      # bool num
        {"user": "u1", "num": 0},                         # non-positive
        {"user": 7, "num": 5},                            # non-str user
        ["not", "a", "dict"],
        None,
        {"user": "u1", "num": 5},                         # one good slot
    ]
    fast = algo.batch_serve_json(model, docs)
    assert fast[:-1] == [None] * (len(docs) - 1)
    assert isinstance(fast[-1], bytes)


def test_fast_path_negative_gate_through_http(stack):
    """The fake_engine stack's serving is not FIRST_PREDICTION_ONLY, so
    this exercises the NEGATIVE gate: the object path still answers."""
    _ps, port, _es, _es_port = stack
    status, body = call(port, "POST", "/queries.json", {"qx": 1})
    assert status == 200


def test_fast_path_served_through_http():
    """POSITIVE gate end-to-end: an ALS engine with stock serving behind
    the REAL server answers plain queries from the bytes fast path, and
    the wire body is exactly the object path's rendering for the same
    singleton batch; filtered queries still take the object path."""
    import threading

    from incubator_predictionio_tpu.data.storage import (
        EngineInstance,
        Storage,
    )
    from incubator_predictionio_tpu.models.recommendation.engine import (
        Query,
        RecommendationServing,
    )
    from incubator_predictionio_tpu.servers.prediction_server import (
        _AsyncPoster,
        _MicroBatcher,
    )
    from incubator_predictionio_tpu.utils import json_codec
    from incubator_predictionio_tpu.utils.http import HttpServer
    from incubator_predictionio_tpu.utils.times import now_utc
    from incubator_predictionio_tpu.workflow.workflow import (
        make_runtime_context,
    )

    algo, model = _als_fixture()
    Storage.configure({
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    })
    now = now_utc()
    srv = PredictionServer.__new__(PredictionServer)
    srv.engine = None
    srv.config = ServerConfig(ip="127.0.0.1", port=0)
    srv.plugin_context = PluginContext()
    srv.ctx = make_runtime_context(None)
    srv._lock = threading.Lock()
    srv.engine_instance = EngineInstance(
        id="t", status="COMPLETED", start_time=now, end_time=now,
        engine_id="t", engine_version="1", engine_variant="t",
        engine_factory="t")
    srv.engine_params = None
    srv.algorithms = [algo]
    srv.serving = RecommendationServing()
    srv.models = [model]
    srv.start_time = now
    srv.request_count = 0
    srv.avg_serving_sec = 0.0
    srv.last_serving_sec = 0.0
    srv.max_batch_served = 0
    srv._conf_server_key = None
    srv.http = HttpServer(srv._build_router(), "127.0.0.1", 0)
    srv._batcher = _MicroBatcher(srv._handle_batch, srv.config.micro_batch)
    srv._feedback_poster = _AsyncPoster("feedback")
    srv._log_poster = _AsyncPoster("log", workers=1)
    port = srv.http.start_background()
    try:
        url = f"http://127.0.0.1:{port}/queries.json"
        req = urllib.request.Request(
            url, data=json.dumps({"user": "u1", "num": 5}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req) as resp:
            wire = resp.read()
        # the wire body is byte-identical to the object path's rendering
        # for the same singleton batch
        objs = dict(algo.batch_predict(model, [(0, Query(user="u1",
                                                         num=5))]))
        assert wire == json.dumps(json_codec.to_jsonable(objs[0])).encode()
        # a filtered query still answers via the object path
        req = urllib.request.Request(
            url, data=json.dumps({"user": "u1", "num": 3,
                                  "blacklist": ["i1"]}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req) as resp:
            doc = json.loads(resp.read())
        assert "i1" not in [s["item"] for s in doc["itemScores"]]
        assert srv.request_count == 2  # stats cover both paths
    finally:
        srv.stop()
        Storage.reset()
