"""Tracing/profiling subsystem tests (SURVEY §5: the TPU build's replacement
for the reference's latency bookkeeping + verbose debugString dumps)."""

import numpy as np
import pytest

from incubator_predictionio_tpu.data.storage import Storage
from incubator_predictionio_tpu.utils import tracing


def test_phase_noop_without_tracer():
    with tracing.phase("anything"):
        pass  # must not raise
    assert tracing.current() is None


def test_tracer_records_phases():
    tracer = tracing.Tracer()
    with tracer.activate():
        assert tracing.current() is tracer
        with tracing.phase("read"):
            pass
        with tracing.phase("train.algo0"):
            pass
        with tracing.phase("read"):   # repeated phases accumulate
            pass
    assert set(tracer.timings) == {"read", "train.algo0"}
    assert all(v >= 0 for v in tracer.timings.values())
    conf = tracer.to_conf()
    assert set(conf) == {"phase.read_s", "phase.train.algo0_s"}
    assert "total=" in tracer.summary()
    assert tracing.current() is None


def test_debug_string_summarizes():
    arr = np.zeros((3, 4), np.float32)
    assert tracing.debug_string(arr) == "<array shape=(3, 4) dtype=float32>"
    s = tracing.debug_string(list(range(100)))
    assert "+90" in s
    s = tracing.debug_string({i: i for i in range(20)})
    assert "+10" in s


def test_run_train_records_phase_timings(tmp_path, monkeypatch):
    """Phase timings land on the completed EngineInstance.runtime_conf."""
    from tests.fake_engine import make_engine, params as make_engine_params

    monkeypatch.setenv("PIO_HOME", str(tmp_path))
    Storage.configure({"PIO_STORAGE_SOURCES_T_TYPE": "memory",
                       "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
                       "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "T",
                       "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
                       "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "T",
                       "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
                       "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "T"})
    try:
        from incubator_predictionio_tpu.workflow import CoreWorkflow

        engine = make_engine()
        instance_id = CoreWorkflow.run_train(engine, make_engine_params())
        instance = Storage.get_meta_data_engine_instances().get(instance_id)
        assert instance.status == "COMPLETED"
        assert "phase.read_s" in instance.runtime_conf
        assert "phase.prepare_s" in instance.runtime_conf
        assert "phase.train.algo0_s" in instance.runtime_conf
        assert "phase.checkpoint_s" in instance.runtime_conf
        assert float(instance.runtime_conf["phase.read_s"]) >= 0
    finally:
        Storage.reset()
