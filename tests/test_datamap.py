"""DataMap/PropertyMap behavior (parity: data/src/test/.../storage/DataMapSpec.scala)."""

import dataclasses
from typing import Optional

import pytest

from incubator_predictionio_tpu.data.datamap import DataMap, DataMapError, PropertyMap
from incubator_predictionio_tpu.utils.times import now_utc


@dataclasses.dataclass
class BasicProperty:
    a: int
    b: str
    c: bool
    d: list[str]
    e: Optional[str] = None
    f: float = 1.5


def test_get_required_and_missing():
    dm = DataMap({"a": 1, "b": "x"})
    assert dm.get("a") == 1
    assert dm.get("a", int) == 1
    with pytest.raises(DataMapError):
        dm.get("nope")


def test_get_null_is_error_opt_is_none():
    dm = DataMap({"a": None})
    with pytest.raises(DataMapError):
        dm.get("a")
    assert dm.opt("a") is None
    assert dm.opt("missing") is None


def test_get_or_else():
    dm = DataMap({"a": 7})
    assert dm.get_or_else("a", 0, int) == 7
    assert dm.get_or_else("z", 42, int) == 42


def test_extract_dataclass():
    dm = DataMap({"a": 3, "b": "hello", "c": True, "d": ["x", "y"]})
    got = dm.extract(BasicProperty)
    assert got == BasicProperty(a=3, b="hello", c=True, d=["x", "y"])


def test_merge_right_biased_and_remove():
    left = DataMap({"a": 1, "b": 2})
    right = DataMap({"b": 3, "c": 4})
    merged = left + right
    assert merged.fields == {"a": 1, "b": 3, "c": 4}
    removed = merged - {"a", "c"}
    assert removed.fields == {"b": 3}


def test_mapping_protocol_and_empty():
    dm = DataMap({"k": "v"})
    assert "k" in dm and len(dm) == 1 and list(dm) == ["k"]
    assert not dm.is_empty
    assert DataMap().is_empty
    assert dm.key_set == frozenset({"k"})


def test_property_map_carries_update_times():
    t = now_utc()
    pm = PropertyMap({"a": 1}, first_updated=t, last_updated=t)
    assert pm.get("a") == 1
    assert pm.first_updated == t and pm.last_updated == t
    assert pm == PropertyMap({"a": 1}, first_updated=t, last_updated=t)
