"""EventServer REST contract (parity: data/src/test/.../api/EventServiceSpec.scala
and the integration suite's EventserverTest with malformed batches)."""

import base64
import json
import urllib.request
import urllib.error

import numpy as np
import pytest

from incubator_predictionio_tpu.data.storage import AccessKey, App, Channel, Storage
from incubator_predictionio_tpu.servers.event_server import (
    EventServer,
    EventServerConfig,
)
from incubator_predictionio_tpu.servers.plugins import EventServerPlugin, PluginContext


class VetoBlocker(EventServerPlugin):
    input_blocker = True

    def process(self, event_info, context):
        if event_info.event.event == "forbidden-event":
            raise ValueError("vetoed by plugin")


class CountingSniffer(EventServerPlugin):
    input_sniffer = True

    def __init__(self):
        self.seen = []

    def process(self, event_info, context):
        self.seen.append(event_info.event.event)

    def handle_rest(self, path, params):
        return {"seen": len(self.seen)}


@pytest.fixture(scope="module")
def server():
    Storage.configure({
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    })
    apps = Storage.get_meta_data_apps()
    app_id = apps.insert(App(0, "srv-app"))
    Storage.get_meta_data_access_keys().insert(AccessKey("testkey", app_id))
    Storage.get_meta_data_access_keys().insert(
        AccessKey("limitedkey", app_id, ("rate",))
    )
    Storage.get_meta_data_channels().insert(Channel(0, "mobile", app_id))
    sniffer = CountingSniffer()
    srv = EventServer(
        EventServerConfig(ip="127.0.0.1", port=0, stats=True),
        PluginContext([VetoBlocker(), sniffer]),
    )
    port = srv.start_background()
    srv.test_port = port
    srv.test_sniffer = sniffer
    yield srv
    srv.stop()
    Storage.reset()


def call(server, method, path, body=None, headers=None):
    url = f"http://127.0.0.1:{server.test_port}{path}"
    data = None
    req_headers = dict(headers or {})
    if body is not None:
        if isinstance(body, (dict, list)):
            data = json.dumps(body).encode()
            req_headers.setdefault("Content-Type", "application/json")
        else:
            data = body if isinstance(body, bytes) else body.encode()
    req = urllib.request.Request(url, data=data, method=method,
                                 headers=req_headers)
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, json.loads(resp.read() or b"null")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"null")


EV = {"event": "rate", "entityType": "user", "entityId": "u1",
      "targetEntityType": "item", "targetEntityId": "i1",
      "properties": {"rating": 5}}


def test_alive(server):
    assert call(server, "GET", "/") == (200, {"status": "alive"})


def test_auth_missing_invalid(server):
    status, body = call(server, "POST", "/events.json", EV)
    assert status == 401
    status, _ = call(server, "POST", "/events.json?accessKey=wrong", EV)
    assert status == 401


def test_auth_basic_header(server):
    creds = base64.b64encode(b"testkey:").decode()
    status, body = call(server, "POST", "/events.json", EV,
                        {"Authorization": f"Basic {creds}"})
    assert status == 201 and "eventId" in body


def test_create_get_delete_event(server):
    status, body = call(server, "POST", "/events.json?accessKey=testkey", EV)
    assert status == 201
    eid = body["eventId"]
    status, got = call(server, "GET", f"/events/{eid}.json?accessKey=testkey")
    assert status == 200
    assert got["event"] == "rate" and got["entityId"] == "u1"
    assert got["properties"] == {"rating": 5}
    status, body = call(server, "DELETE", f"/events/{eid}.json?accessKey=testkey")
    assert status == 200 and body == {"message": "Found"}
    status, _ = call(server, "GET", f"/events/{eid}.json?accessKey=testkey")
    assert status == 404


def test_malformed_event_400(server):
    status, body = call(server, "POST", "/events.json?accessKey=testkey",
                        {"entityType": "user"})
    assert status == 400
    status, body = call(server, "POST", "/events.json?accessKey=testkey",
                        body=b"not json")
    assert status == 400
    # reserved-name violation
    bad = dict(EV, event="$badname")
    status, body = call(server, "POST", "/events.json?accessKey=testkey", bad)
    assert status == 400


def test_allowed_events_enforced(server):
    status, _ = call(server, "POST", "/events.json?accessKey=limitedkey", EV)
    assert status == 201
    buy = dict(EV, event="buy")
    status, body = call(server, "POST", "/events.json?accessKey=limitedkey", buy)
    assert status == 403
    assert "not allowed" in body["message"]


def test_channel_auth_and_isolation(server):
    status, body = call(
        server, "POST", "/events.json?accessKey=testkey&channel=mobile",
        dict(EV, entityId="chan-user"),
    )
    assert status == 201
    status, _ = call(
        server, "POST", "/events.json?accessKey=testkey&channel=nope", EV
    )
    assert status == 401
    # event only visible in its channel
    status, found = call(
        server, "GET",
        "/events.json?accessKey=testkey&channel=mobile&entityId=chan-user",
    )
    assert status == 200 and len(found) == 1
    status, _ = call(
        server, "GET", "/events.json?accessKey=testkey&entityId=chan-user"
    )
    assert status == 404


def test_query_events(server):
    for i in range(3):
        call(server, "POST", "/events.json?accessKey=testkey",
             dict(EV, entityId=f"qu{i}", event="view"))
    status, found = call(server, "GET",
                         "/events.json?accessKey=testkey&event=view")
    assert status == 200 and len(found) >= 3
    status, found = call(
        server, "GET",
        "/events.json?accessKey=testkey&event=view&limit=2&reversed=true",
    )
    assert status == 200 and len(found) == 2
    status, _ = call(server, "GET",
                     "/events.json?accessKey=testkey&event=nothing-here")
    assert status == 404
    status, _ = call(server, "GET",
                     "/events.json?accessKey=testkey&startTime=garbage")
    assert status == 400


def test_batch_events(server):
    batch = [
        dict(EV, entityId="b1"),
        {"entityType": "user"},  # malformed
        dict(EV, entityId="b2", event="forbidden-event"),  # vetoed by plugin
    ]
    status, results = call(server, "POST",
                           "/batch/events.json?accessKey=testkey", batch)
    assert status == 200
    assert results[0]["status"] == 201
    assert results[1]["status"] == 400
    assert results[2]["status"] == 500  # blocker veto surfaces per-event
    # batch too large
    status, body = call(server, "POST", "/batch/events.json?accessKey=testkey",
                        [EV] * 51)
    assert status == 400
    assert "50" in body["message"]


def test_stats(server):
    status, body = call(server, "GET", "/stats.json?accessKey=testkey")
    assert status == 200
    assert body["appId"] == 1
    assert any(s["status"] == 201 for s in body["status"])


def test_webhook_segmentio(server):
    payload = {
        "version": "2", "type": "track", "userId": "seg-user",
        "event": "Signed Up", "properties": {"plan": "Pro"},
        "timestamp": "2020-02-02T02:02:02.000Z",
    }
    status, body = call(server, "POST",
                        "/webhooks/segmentio.json?accessKey=testkey", payload)
    assert status == 201
    status, found = call(
        server, "GET", "/events.json?accessKey=testkey&entityId=seg-user"
    )
    assert status == 200
    assert found[0]["event"] == "track"
    assert found[0]["properties"]["event"] == "Signed Up"
    # probe + unknown connector
    assert call(server, "GET",
                "/webhooks/segmentio.json?accessKey=testkey")[0] == 200
    assert call(server, "POST", "/webhooks/nope.json?accessKey=testkey",
                payload)[0] == 404
    # bad payload
    status, _ = call(server, "POST",
                     "/webhooks/segmentio.json?accessKey=testkey",
                     {"type": "track"})
    assert status == 400


def test_webhook_mailchimp_form(server):
    form = ("type=subscribe&fired_at=2009-03-26 21:35:57"
            "&data[id]=8a25ff1d98&data[list_id]=a6b5da1054"
            "&data[email]=api@mailchimp.com"
            "&data[merges][EMAIL]=api@mailchimp.com"
            "&data[merges][FNAME]=MailChimp")
    status, body = call(
        server, "POST", "/webhooks/mailchimp.form?accessKey=testkey",
        body=form.encode(),
        headers={"Content-Type": "application/x-www-form-urlencoded"},
    )
    assert status == 201
    status, found = call(
        server, "GET",
        "/events.json?accessKey=testkey&entityId=api@mailchimp.com",
    )
    assert status == 200
    assert found[0]["event"] == "subscribe"
    assert found[0]["properties"]["merges"]["FNAME"] == "MailChimp"
    assert found[0]["eventTime"].startswith("2009-03-26T21:35:57")


def test_plugins_routes(server):
    status, body = call(server, "GET", "/plugins.json")
    assert status == 200
    assert "VetoBlocker" in body["plugins"]["inputblockers"]
    assert "CountingSniffer" in body["plugins"]["inputsniffers"]
    status, body = call(server, "GET", "/plugins/CountingSniffer/anything")
    assert status == 200 and body["seen"] >= 1
    assert call(server, "GET", "/plugins/Nope/x")[0] == 404


def test_unknown_route_and_method(server):
    assert call(server, "GET", "/nope.json")[0] == 404
    assert call(server, "DELETE", "/events.json?accessKey=testkey")[0] == 405


class TestDocGateDifferential:
    """The doc-level batch gate (uniform_interactions_from_docs) must
    accept exactly what parsing each doc into an Event and running the
    Event-level gate would accept — except the two doc-only screens
    (unknown keys, explicit creationTime), which may only be STRICTER
    (doc gate rejects → generic path; never the other way). When both
    accept, the produced bundles must be identical."""

    def _cases(self):
        base_doc = {
            "event": "rate", "entityType": "user", "entityId": "u1",
            "targetEntityType": "item", "targetEntityId": "i1",
            "properties": {"rating": 3.0},
        }

        def batch(mut=None, idx=0, n=10):
            docs = [dict(base_doc, entityId=f"u{k}",
                         properties={"rating": float(1 + k % 5)})
                    for k in range(n)]
            if mut:
                docs[idx] = mut(dict(docs[idx]))
            return docs

        def set_(key, val):
            def m(d):
                d[key] = val
                return d
            return m

        def set_prop(val):
            def m(d):
                d["properties"] = val
                return d
            return m

        return [
            ("uniform", batch()),
            ("reserved name", batch(set_("event", "$set"))),
            ("pio_ event name", batch(set_("event", "pio_rate"))),
            ("empty name", batch(set_("event", ""))),
            ("mixed names", batch(set_("event", "view"), idx=3)),
            ("no target", batch(set_("targetEntityId", None), idx=2)),
            ("empty entity", batch(set_("entityId", ""), idx=5)),
            ("pio_ entity type", batch(set_("entityType", "pio_x"))),
            ("pio_pr builtin ok", batch(set_("targetEntityType", "pio_pr"))),
            ("pio_ property", batch(set_prop({"pio_v": 1.0}))),
            ("two props", batch(set_prop({"a": 1.0, "b": 2.0}), idx=7)),
            ("bool value", batch(set_prop({"rating": True}), idx=1)),
            ("string value", batch(set_prop({"rating": "x"}), idx=4)),
            ("f32-inexact", batch(set_prop({"rating": 4.1}), idx=6)),
            ("explicit id", batch(set_("eventId", "a" * 32), idx=0)),
            ("prId", batch(set_("prId", "p1"), idx=8)),
            ("non-utc time", batch(
                set_("eventTime", "2026-07-15T10:00:00.000+09:00"), idx=3)),
            ("utc time", batch(
                set_("eventTime", "2026-07-15T10:00:00.000Z"), idx=3)),
            ("bad time", batch(set_("eventTime", "not-a-date"), idx=2)),
        ]

    def test_doc_gate_matches_event_gate(self):
        import numpy as np

        from incubator_predictionio_tpu.data.event import (
            Event,
            EventValidationError,
            validate_event,
        )
        from incubator_predictionio_tpu.data.storage.base import (
            uniform_interactions,
            uniform_interactions_from_docs,
        )

        for label, docs in self._cases():
            doc_res = uniform_interactions_from_docs(docs)
            try:
                events = [Event.from_jsonable(d) for d in docs]
                for e in events:
                    validate_event(e)
                ev_res = uniform_interactions(events)
            except (ValueError, EventValidationError):
                ev_res = None
            if ev_res is None:
                assert doc_res is None, label
                continue
            # the Event gate accepted; the doc gate must agree (none of
            # the cases above carry unknown keys / creationTime, the two
            # allowed doc-stricter screens) and produce the same bundle
            assert doc_res is not None, label
            for a, b, what in [
                (doc_res[0].user_idx, ev_res[0].user_idx, "user_idx"),
                (doc_res[0].item_idx, ev_res[0].item_idx, "item_idx"),
                (doc_res[0].values, ev_res[0].values, "values"),
            ]:
                np.testing.assert_array_equal(a, b, err_msg=f"{label}:{what}")
            assert list(doc_res[0].user_ids) == list(ev_res[0].user_ids), label
            assert list(doc_res[0].item_ids) == list(ev_res[0].item_ids), label
            assert doc_res[1:5] == ev_res[1:5], label

    def test_doc_only_screens_are_stricter_not_looser(self):
        from incubator_predictionio_tpu.data.storage.base import (
            uniform_interactions_from_docs,
        )

        base_doc = {
            "event": "rate", "entityType": "user", "entityId": "u1",
            "targetEntityType": "item", "targetEntityId": "i1",
            "properties": {"rating": 3.0},
        }
        docs = [dict(base_doc, entityId=f"u{k}") for k in range(10)]
        docs[4]["creationTime"] = "2026-07-15T10:00:00.000Z"
        assert uniform_interactions_from_docs(docs) is None
        docs = [dict(base_doc, entityId=f"u{k}") for k in range(10)]
        docs[2]["unknownField"] = 1
        assert uniform_interactions_from_docs(docs) is None


import contextlib


@contextlib.contextmanager
def _cpplog_server(tmp_path, access_key="fk", stats=False):
    """A live EventServer over a cpplog event store (the fast-path
    backend), torn down server-first on every exit path."""
    Storage.reset()
    Storage.configure({
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_SOURCES_EV_TYPE": "cpplog",
        "PIO_STORAGE_SOURCES_EV_PATH": str(tmp_path),
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "EV",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    })
    srv = None
    try:
        app_id = Storage.get_meta_data_apps().insert(App(0, "fastapp"))
        Storage.get_meta_data_access_keys().insert(
            AccessKey(access_key, app_id))
        srv = EventServer(EventServerConfig(ip="127.0.0.1", port=0,
                                            stats=stats))
        port = srv.start_background()
        yield srv, port
    finally:
        if srv is not None:
            srv.stop()
        Storage.reset()


def _uniform_batch_docs(n):
    return [{"event": "rate", "entityType": "user",
             "entityId": f"u{k}", "targetEntityType": "item",
             "targetEntityId": f"i{k % 3}",
             "properties": {"rating": float(k % 5)}}
            for k in range(n)]


def test_batch_fast_path_ids_resolve(tmp_path):
    """REST fast-path ids must be the ids the store actually holds."""
    with _cpplog_server(tmp_path) as (srv, port):
        batch = _uniform_batch_docs(20)
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/batch/events.json?accessKey=fk",
            data=json.dumps(batch).encode(),
            headers={"Content-Type": "application/json"})
        res = json.load(urllib.request.urlopen(req))
        assert all(r["status"] == 201 for r in res)
        for src, r in zip(batch, res):
            got = json.load(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/events/{r['eventId']}.json"
                "?accessKey=fk"))
            assert got["entityId"] == src["entityId"]
            assert got["properties"]["rating"] == src["properties"]["rating"]


def test_batch_with_blocker_takes_generic_path_with_full_visibility(
        tmp_path):
    """A registered input blocker must see EVERY event of a uniform batch
    (the columnar fast path skips per-Event plugin visibility, so it must
    disengage), and its veto surfaces as a per-event 500 — the
    reference's blocker-veto status (EventServer.scala:409-412; 403 is
    reserved for auth / allowed-names) — without touching the other
    slots."""
    from incubator_predictionio_tpu.servers.plugins import (
        EventServerPlugin as _Plugin,
    )

    class Veto(_Plugin):
        input_blocker = True
        seen: list = []

        def process(self, event_info, context):
            Veto.seen.append(event_info.event.entity_id)
            if event_info.event.entity_id == "u3":
                raise ValueError("u3 is banned")

    with _cpplog_server(tmp_path, access_key="bk") as (srv, port):
        srv.plugin_context.plugins.append(Veto())
        batch = _uniform_batch_docs(12)  # uniform — fast-path shaped
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/batch/events.json?accessKey=bk",
            data=json.dumps(batch).encode(),
            headers={"Content-Type": "application/json"})
        res = json.load(urllib.request.urlopen(req))
        # per-event isolation: only u3 blocked; everything else landed
        assert [r["status"] for r in res] == [
            201 if k != 3 else 500 for k in range(12)], res
        # the blocker saw every event — the columnar fast path (which has
        # no per-Event hook) must have disengaged
        assert Veto.seen == [f"u{k}" for k in range(12)]


def test_concurrent_batches_group_commit(tmp_path):
    """Concurrent uniform batches over the group-committing cpplog store:
    every event lands exactly once, every returned id resolves, and ids
    never collide across merged sub-batches (cpplog._commit_pending_locked
    slices one seed run per merge)."""
    import threading

    with _cpplog_server(tmp_path) as (srv, port):
        n_threads, batches_each, bs = 8, 6, 12
        all_ids: list = []
        errs: list = []
        lock = threading.Lock()

        def worker(t: int) -> None:
            try:
                for b in range(batches_each):
                    docs = [{
                        "event": "rate",
                        "entityType": "user",
                        "entityId": f"t{t}_b{b}_u{k}",
                        "targetEntityType": "item",
                        "targetEntityId": f"i{k}",
                        "properties": {"rating": float(1 + k % 5)},
                    } for k in range(bs)]
                    req = urllib.request.Request(
                        f"http://127.0.0.1:{port}/batch/events.json"
                        "?accessKey=fk",
                        data=json.dumps(docs).encode(),
                        headers={"Content-Type": "application/json"})
                    res = json.load(urllib.request.urlopen(req))
                    assert all(r["status"] == 201 for r in res), res
                    with lock:
                        all_ids.extend(r["eventId"] for r in res)
            except Exception as e:  # surface in the main thread
                errs.append(e)

        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(n_threads)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert not errs, errs
        expect = n_threads * batches_each * bs
        assert len(all_ids) == expect
        assert len(set(all_ids)) == expect  # no id collisions across merges
        # total landed count is exact (no loss, no duplication)
        got = json.load(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/events.json?accessKey=fk"
            f"&limit={expect + 100}"))
        assert len(got) == expect


def test_stats_reports_group_commit_counters(tmp_path):
    """/stats.json over a group-committing backend carries the coalescing
    counters, and they reconcile with what was posted."""
    with _cpplog_server(tmp_path, stats=True) as (srv, port):
        for b in range(3):
            docs = [{
                "event": "rate", "entityType": "user",
                "entityId": f"s{b}_{k}", "targetEntityType": "item",
                "targetEntityId": f"i{k}",
                "properties": {"rating": 1.0},
            } for k in range(10)]
            urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{port}/batch/events.json?accessKey=fk",
                data=json.dumps(docs).encode(),
                headers={"Content-Type": "application/json"})).read()
        got = json.load(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/stats.json?accessKey=fk"))
        gc = got["groupCommit"]
        assert gc["events"] == 30
        assert gc["callerBatches"] == 3
        assert 1 <= gc["appends"] <= 3
        assert gc["maxMergedEvents"] >= 10
        assert gc["meanEventsPerAppend"] >= 10.0


class TestNativeBodyParser:
    """native/src/jsonparse.cc vs the Python doc gate: the native
    acceptance set must be a strict subset with IDENTICAL output."""

    def _gate(self, body: bytes, max_n: int = 50):
        from incubator_predictionio_tpu.data.storage.base import (
            uniform_interactions_from_body,
        )
        return uniform_interactions_from_body(body, max_n)

    def _pygate(self, body: bytes):
        from incubator_predictionio_tpu.data.storage.base import (
            uniform_interactions_from_docs,
        )
        try:
            docs = json.loads(body)
        except ValueError:
            return None
        if not isinstance(docs, list):
            return None
        return uniform_interactions_from_docs(docs)

    def _assert_subset_equal(self, body: bytes):
        nat = self._gate(body)
        if nat is None:
            return False
        py = self._pygate(body)
        assert py is not None, f"native accepted what python rejects: {body!r}"
        ni, ne, nt, nn, nv, ntm = nat
        pi, pe, pt, pn, pv, ptm = py
        assert (ne, nt, nn, nv) == (pe, pt, pn, pv)
        assert ntm is None and ptm is None
        assert np.array_equal(ni.user_idx, pi.user_idx)
        assert np.array_equal(ni.item_idx, pi.item_idx)
        assert np.array_equal(ni.values, pi.values)
        assert list(ni.user_ids) == list(pi.user_ids)
        assert list(ni.item_ids) == list(pi.item_ids)
        return True

    def test_plain_batch_accepted_identical(self):
        docs = [{"event": "rate", "entityType": "user",
                 "entityId": f"u{k % 5}", "targetEntityType": "item",
                 "targetEntityId": f"i{k}",
                 "properties": {"rating": float(1 + k % 5)}}
                for k in range(20)]
        assert self._assert_subset_equal(json.dumps(docs).encode())

    def test_number_forms_and_unicode(self):
        docs = [{"event": "rate", "entityType": "user",
                 "entityId": "usér-ñ", "targetEntityType": "item",
                 "targetEntityId": "i1", "properties": {"rating": 2}},
                {"event": "rate", "entityType": "user", "entityId": "u2",
                 "targetEntityType": "item", "targetEntityId": "i2",
                 "properties": {"rating": 2.5e2}}]
        body = json.dumps(docs, ensure_ascii=False).encode()
        assert self._assert_subset_equal(body)

    def test_fallback_cases_never_accepted_wrongly(self):
        base_doc = {"event": "rate", "entityType": "user",
                    "entityId": "u1", "targetEntityType": "item",
                    "targetEntityId": "i1", "properties": {"rating": 1.0}}
        rejected = [
            [dict(base_doc, eventTime="2026-01-01T00:00:00.000Z")],
            [dict(base_doc, entityId="a\\\"b")],          # escapes
            [dict(base_doc, extra=1)],                     # unknown key
            [dict(base_doc, event="$set")],                # reserved
            [dict(base_doc, properties={"r": 0.1})],       # not f32-exact
            [dict(base_doc, properties={"r": True})],      # bool
            [dict(base_doc, properties={})],               # empty props
            [dict(base_doc, entityId="")],                 # empty id
            "not-a-list",
            [],
        ]
        for case in rejected:
            body = (json.dumps(case).encode()
                    if not isinstance(case, bytes) else case)
            nat = self._gate(body)
            if nat is not None:
                # native accepted: python MUST accept identically
                self._assert_subset_equal(body)

    def test_invalid_utf8_rejected(self):
        """Raw non-UTF-8 bytes in any string must fall back (json.loads
        on the generic path 400s them; persisting undecodable ids or
        crashing the handler would both break the subset contract)."""
        doc = (b'[{"event": "rate", "entityType": "user", '
               b'"entityId": "u\xff\xfe1", "targetEntityType": "item", '
               b'"targetEntityId": "i1", "properties": {"rating": 1.0}}]')
        assert self._gate(doc) is None
        # overlong encoding of '/' (0xC0 0xAF) and a lone surrogate
        for bad in (b"\xc0\xaf", b"\xed\xa0\x80"):
            doc2 = (b'[{"event": "rate", "entityType": "user", '
                    b'"entityId": "u' + bad + b'", '
                    b'"targetEntityType": "item", "targetEntityId": "i1", '
                    b'"properties": {"rating": 1.0}}]')
            assert self._gate(doc2) is None

    def test_randomized_differential(self):
        rng = np.random.default_rng(11)
        keys = ["event", "entityType", "entityId", "targetEntityType",
                "targetEntityId", "properties", "eventTime", "bogus"]
        accepted = 0
        for trial in range(300):
            n = int(rng.integers(1, 12))
            docs = []
            for _ in range(n):
                d = {"event": "rate", "entityType": "user",
                     "entityId": f"u{int(rng.integers(0, 6))}",
                     "targetEntityType": "item",
                     "targetEntityId": f"i{int(rng.integers(0, 6))}",
                     "properties": {"rating": float(int(rng.integers(1, 6)))}}
                # random mutations
                for _m in range(int(rng.integers(0, 3))):
                    k = keys[int(rng.integers(0, len(keys)))]
                    roll = rng.random()
                    if roll < 0.3 and k in d:
                        del d[k]
                    elif roll < 0.6:
                        d[k] = ["x", 1, None][int(rng.integers(0, 3))]
                    elif k == "properties":
                        d[k] = {"rating": float(rng.normal())}
                    else:
                        d[k] = f"v{int(rng.integers(0, 4))}"
                docs.append(d)
            body = json.dumps(docs).encode()
            if self._assert_subset_equal(body):
                accepted += 1
        assert accepted >= 10  # the harness must exercise the accept leg
