"""EventServer REST contract (parity: data/src/test/.../api/EventServiceSpec.scala
and the integration suite's EventserverTest with malformed batches)."""

import base64
import json
import urllib.request
import urllib.error

import pytest

from incubator_predictionio_tpu.data.storage import AccessKey, App, Channel, Storage
from incubator_predictionio_tpu.servers.event_server import (
    EventServer,
    EventServerConfig,
)
from incubator_predictionio_tpu.servers.plugins import EventServerPlugin, PluginContext


class VetoBlocker(EventServerPlugin):
    input_blocker = True

    def process(self, event_info, context):
        if event_info.event.event == "forbidden-event":
            raise ValueError("vetoed by plugin")


class CountingSniffer(EventServerPlugin):
    input_sniffer = True

    def __init__(self):
        self.seen = []

    def process(self, event_info, context):
        self.seen.append(event_info.event.event)

    def handle_rest(self, path, params):
        return {"seen": len(self.seen)}


@pytest.fixture(scope="module")
def server():
    Storage.configure({
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    })
    apps = Storage.get_meta_data_apps()
    app_id = apps.insert(App(0, "srv-app"))
    Storage.get_meta_data_access_keys().insert(AccessKey("testkey", app_id))
    Storage.get_meta_data_access_keys().insert(
        AccessKey("limitedkey", app_id, ("rate",))
    )
    Storage.get_meta_data_channels().insert(Channel(0, "mobile", app_id))
    sniffer = CountingSniffer()
    srv = EventServer(
        EventServerConfig(ip="127.0.0.1", port=0, stats=True),
        PluginContext([VetoBlocker(), sniffer]),
    )
    port = srv.start_background()
    srv.test_port = port
    srv.test_sniffer = sniffer
    yield srv
    srv.stop()
    Storage.reset()


def call(server, method, path, body=None, headers=None):
    url = f"http://127.0.0.1:{server.test_port}{path}"
    data = None
    req_headers = dict(headers or {})
    if body is not None:
        if isinstance(body, (dict, list)):
            data = json.dumps(body).encode()
            req_headers.setdefault("Content-Type", "application/json")
        else:
            data = body if isinstance(body, bytes) else body.encode()
    req = urllib.request.Request(url, data=data, method=method,
                                 headers=req_headers)
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, json.loads(resp.read() or b"null")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"null")


EV = {"event": "rate", "entityType": "user", "entityId": "u1",
      "targetEntityType": "item", "targetEntityId": "i1",
      "properties": {"rating": 5}}


def test_alive(server):
    assert call(server, "GET", "/") == (200, {"status": "alive"})


def test_auth_missing_invalid(server):
    status, body = call(server, "POST", "/events.json", EV)
    assert status == 401
    status, _ = call(server, "POST", "/events.json?accessKey=wrong", EV)
    assert status == 401


def test_auth_basic_header(server):
    creds = base64.b64encode(b"testkey:").decode()
    status, body = call(server, "POST", "/events.json", EV,
                        {"Authorization": f"Basic {creds}"})
    assert status == 201 and "eventId" in body


def test_create_get_delete_event(server):
    status, body = call(server, "POST", "/events.json?accessKey=testkey", EV)
    assert status == 201
    eid = body["eventId"]
    status, got = call(server, "GET", f"/events/{eid}.json?accessKey=testkey")
    assert status == 200
    assert got["event"] == "rate" and got["entityId"] == "u1"
    assert got["properties"] == {"rating": 5}
    status, body = call(server, "DELETE", f"/events/{eid}.json?accessKey=testkey")
    assert status == 200 and body == {"message": "Found"}
    status, _ = call(server, "GET", f"/events/{eid}.json?accessKey=testkey")
    assert status == 404


def test_malformed_event_400(server):
    status, body = call(server, "POST", "/events.json?accessKey=testkey",
                        {"entityType": "user"})
    assert status == 400
    status, body = call(server, "POST", "/events.json?accessKey=testkey",
                        body=b"not json")
    assert status == 400
    # reserved-name violation
    bad = dict(EV, event="$badname")
    status, body = call(server, "POST", "/events.json?accessKey=testkey", bad)
    assert status == 400


def test_allowed_events_enforced(server):
    status, _ = call(server, "POST", "/events.json?accessKey=limitedkey", EV)
    assert status == 201
    buy = dict(EV, event="buy")
    status, body = call(server, "POST", "/events.json?accessKey=limitedkey", buy)
    assert status == 403
    assert "not allowed" in body["message"]


def test_channel_auth_and_isolation(server):
    status, body = call(
        server, "POST", "/events.json?accessKey=testkey&channel=mobile",
        dict(EV, entityId="chan-user"),
    )
    assert status == 201
    status, _ = call(
        server, "POST", "/events.json?accessKey=testkey&channel=nope", EV
    )
    assert status == 401
    # event only visible in its channel
    status, found = call(
        server, "GET",
        "/events.json?accessKey=testkey&channel=mobile&entityId=chan-user",
    )
    assert status == 200 and len(found) == 1
    status, _ = call(
        server, "GET", "/events.json?accessKey=testkey&entityId=chan-user"
    )
    assert status == 404


def test_query_events(server):
    for i in range(3):
        call(server, "POST", "/events.json?accessKey=testkey",
             dict(EV, entityId=f"qu{i}", event="view"))
    status, found = call(server, "GET",
                         "/events.json?accessKey=testkey&event=view")
    assert status == 200 and len(found) >= 3
    status, found = call(
        server, "GET",
        "/events.json?accessKey=testkey&event=view&limit=2&reversed=true",
    )
    assert status == 200 and len(found) == 2
    status, _ = call(server, "GET",
                     "/events.json?accessKey=testkey&event=nothing-here")
    assert status == 404
    status, _ = call(server, "GET",
                     "/events.json?accessKey=testkey&startTime=garbage")
    assert status == 400


def test_batch_events(server):
    batch = [
        dict(EV, entityId="b1"),
        {"entityType": "user"},  # malformed
        dict(EV, entityId="b2", event="forbidden-event"),  # vetoed by plugin
    ]
    status, results = call(server, "POST",
                           "/batch/events.json?accessKey=testkey", batch)
    assert status == 200
    assert results[0]["status"] == 201
    assert results[1]["status"] == 400
    assert results[2]["status"] == 500  # blocker veto surfaces per-event
    # batch too large
    status, body = call(server, "POST", "/batch/events.json?accessKey=testkey",
                        [EV] * 51)
    assert status == 400
    assert "50" in body["message"]


def test_stats(server):
    status, body = call(server, "GET", "/stats.json?accessKey=testkey")
    assert status == 200
    assert body["appId"] == 1
    assert any(s["status"] == 201 for s in body["status"])


def test_webhook_segmentio(server):
    payload = {
        "version": "2", "type": "track", "userId": "seg-user",
        "event": "Signed Up", "properties": {"plan": "Pro"},
        "timestamp": "2020-02-02T02:02:02.000Z",
    }
    status, body = call(server, "POST",
                        "/webhooks/segmentio.json?accessKey=testkey", payload)
    assert status == 201
    status, found = call(
        server, "GET", "/events.json?accessKey=testkey&entityId=seg-user"
    )
    assert status == 200
    assert found[0]["event"] == "track"
    assert found[0]["properties"]["event"] == "Signed Up"
    # probe + unknown connector
    assert call(server, "GET",
                "/webhooks/segmentio.json?accessKey=testkey")[0] == 200
    assert call(server, "POST", "/webhooks/nope.json?accessKey=testkey",
                payload)[0] == 404
    # bad payload
    status, _ = call(server, "POST",
                     "/webhooks/segmentio.json?accessKey=testkey",
                     {"type": "track"})
    assert status == 400


def test_webhook_mailchimp_form(server):
    form = ("type=subscribe&fired_at=2009-03-26 21:35:57"
            "&data[id]=8a25ff1d98&data[list_id]=a6b5da1054"
            "&data[email]=api@mailchimp.com"
            "&data[merges][EMAIL]=api@mailchimp.com"
            "&data[merges][FNAME]=MailChimp")
    status, body = call(
        server, "POST", "/webhooks/mailchimp.form?accessKey=testkey",
        body=form.encode(),
        headers={"Content-Type": "application/x-www-form-urlencoded"},
    )
    assert status == 201
    status, found = call(
        server, "GET",
        "/events.json?accessKey=testkey&entityId=api@mailchimp.com",
    )
    assert status == 200
    assert found[0]["event"] == "subscribe"
    assert found[0]["properties"]["merges"]["FNAME"] == "MailChimp"
    assert found[0]["eventTime"].startswith("2009-03-26T21:35:57")


def test_plugins_routes(server):
    status, body = call(server, "GET", "/plugins.json")
    assert status == 200
    assert "VetoBlocker" in body["plugins"]["inputblockers"]
    assert "CountingSniffer" in body["plugins"]["inputsniffers"]
    status, body = call(server, "GET", "/plugins/CountingSniffer/anything")
    assert status == 200 and body["seen"] >= 1
    assert call(server, "GET", "/plugins/Nope/x")[0] == 404


def test_unknown_route_and_method(server):
    assert call(server, "GET", "/nope.json")[0] == 404
    assert call(server, "DELETE", "/events.json?accessKey=testkey")[0] == 405
