"""Mesh-sharded factor tables: parity, resharding, fold-in, top-k merge.

The ALX-style placement refactor's safety net, on the 8-virtual-device
CPU sim (conftest forces ``--xla_force_host_platform_device_count=8``):

- **factor parity** — ``als_train_placed`` matches the single-chip
  trainer at mesh shapes {1, 2, 4, 8}, explicit AND implicit, fused
  kernel on and off, over BOTH gather strategies (transient all-gather
  and the slice-resident ppermute ring); allgather × fused is bitwise
  against the single-chip fused run (same per-bucket systems, same
  reduction order), everything else ≤ 1e-5 relative;
- **continuation retrain under a placement** — matches the single-chip
  retrain, stays ONE device dispatch (splice scatters inside the
  training jit), reuses the sharded plan on a same-geometry retrain,
  and *invalidates* (rebuild once, correct results) when the mesh shape
  changes under a live plan key — the resharding contract;
- **continue_state across mesh shapes** — a model trained at one mesh
  shape re-distributes under another via ``place_state``;
- **fold-in on a sharded frozen table** — the speed layer's ladder
  solves against a distributed other-side table match the replicated
  solver (GSPMD routes each history's gathers to the owning shard);
- **sharded top-k** — per-shard partial top-k + all-gather merge is
  equivalent to the dense reference, including exclusions, allowed
  masks and placement-padding masking, and serving auto-routes to it
  whenever the item table is actually distributed;
- **seams** — ``PIO_MESH_DEVICES`` caps the standard mesh (the
  sub-mesh test seam) and ``PIO_SHARD_TABLES``/``model_parallelism``
  gate ``placement_for_ctx``; ``pio_shard_*`` gauges are booked by
  placed training.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from incubator_predictionio_tpu.obs import metrics as obs_metrics
from incubator_predictionio_tpu.ops import als, retrain, topk
from incubator_predictionio_tpu.parallel.mesh import make_mesh
from incubator_predictionio_tpu.parallel.placement import (
    FactorPlacement,
    is_distributed,
    make_placement,
    placement_for_ctx,
)
from incubator_predictionio_tpu.speed.foldin import FoldInSolver

N_USERS, N_ITEMS, NNZ, RANK = 50, 37, 600, 8


@pytest.fixture(autouse=True)
def _fresh_plans():
    retrain.drop_plans()
    yield
    retrain.drop_plans()


def _need(n: int):
    if jax.device_count() < n:
        pytest.skip(f"needs {n} devices, have {jax.device_count()}")


def _mesh(n: int):
    _need(n)
    return make_mesh(devices=jax.devices()[:n])


def _data(seed=0, nnz=NNZ, n_users=N_USERS, n_items=N_ITEMS):
    rng = np.random.default_rng(seed)
    return (rng.integers(0, n_users, nnz).astype(np.int32),
            rng.integers(0, n_items, nnz).astype(np.int32),
            rng.uniform(1, 5, nnz).astype(np.float32))


def _rel(got, ref):
    got, ref = np.asarray(got), np.asarray(ref)
    return float(np.max(np.abs(got - ref))
                 / max(float(np.max(np.abs(ref))), 1e-9))


def _force_fused(monkeypatch):
    """Interpret-mode hook: route every bucket through the fused
    gather+Gram+CG Pallas kernel (the PR 7 test convention)."""
    monkeypatch.setattr(als, "_ALS_KERNEL", "on")
    monkeypatch.setattr(als, "_KERNEL_MIN_D", 0)
    monkeypatch.setenv("PIO_ALS_FUSED_GRAM", "on")


# ---------------------------------------------------------------------------
# training parity: sharded vs single-chip
# ---------------------------------------------------------------------------

class TestPlacedTrainingParity:
    """als_train_placed ≡ the single-chip path at every mesh shape."""

    def _reference(self, users, items, vals, implicit):
        if implicit:
            return als.als_train_implicit(
                users, items, vals, n_users=N_USERS, n_items=N_ITEMS,
                rank=RANK, iterations=2, l2=0.1, seed=0)
        state, _ = als.als_train(
            users, items, vals, n_users=N_USERS, n_items=N_ITEMS,
            rank=RANK, iterations=2, l2=0.1, seed=0)
        return state

    def _placed(self, n, gather, implicit, monkeypatch):
        users, items, vals = _data()
        monkeypatch.setenv("PIO_SHARD_GATHER", gather)
        placement = make_placement(_mesh(n), N_USERS, N_ITEMS)
        out = als.als_train_placed(
            users, items, vals, N_USERS, N_ITEMS, placement=placement,
            rank=RANK, iterations=2, l2=0.1, seed=0, implicit=implicit)
        assert out.placement is placement
        if n > 1:
            assert is_distributed(out.user_factors)
            assert is_distributed(out.item_factors)
        ref = self._reference(users, items, vals, implicit)
        return placement.unplace_state(out), ref

    # gather strategy alternates with the mesh shape so both the
    # transient all-gather and the ppermute ring cover multi-shard
    # meshes; a dedicated test below pins allgather ≡ ring at n=4
    @pytest.mark.parametrize("implicit", [False, True])
    @pytest.mark.parametrize("n,gather", [
        (1, "allgather"), (2, "ring"), (4, "allgather"), (8, "ring"),
    ])
    def test_unfused_parity(self, n, gather, implicit, monkeypatch):
        monkeypatch.setattr(als, "_ALS_KERNEL", "off")
        got, ref = self._placed(n, gather, implicit, monkeypatch)
        assert _rel(got.user_factors, ref.user_factors) < 1e-5
        assert _rel(got.item_factors, ref.item_factors) < 1e-5

    @pytest.mark.parametrize("implicit", [False, True])
    @pytest.mark.parametrize("n,gather", [
        (2, "allgather"), (4, "ring"), (8, "allgather"),
    ])
    def test_fused_parity(self, n, gather, implicit, monkeypatch):
        """The fused Pallas kernel runs per shard INSIDE shard_map on
        shard-local table slices; allgather mode solves the identical
        per-bucket systems in the identical order as the single-chip
        fused run, so parity there is BITWISE."""
        _force_fused(monkeypatch)
        cfg = als._placed_cfg(
            make_placement(_mesh(n), N_USERS, N_ITEMS), RANK, implicit,
            True, 0.1, 1.0, jnp.float32, jax.lax.Precision.HIGHEST,
            als._CG_ITERS)
        assert cfg.fused_u and cfg.fused_i  # routing actually engaged
        got, ref = self._placed(n, gather, implicit, monkeypatch)
        if gather == "allgather":
            assert np.array_equal(np.asarray(got.user_factors),
                                  np.asarray(ref.user_factors))
            assert np.array_equal(np.asarray(got.item_factors),
                                  np.asarray(ref.item_factors))
        else:
            assert _rel(got.user_factors, ref.user_factors) < 1e-5
            assert _rel(got.item_factors, ref.item_factors) < 1e-5

    def test_allgather_matches_ring(self, monkeypatch):
        users, items, vals = _data(7)
        outs = {}
        for gather in ("allgather", "ring"):
            monkeypatch.setenv("PIO_SHARD_GATHER", gather)
            placement = make_placement(_mesh(4), N_USERS, N_ITEMS)
            outs[gather] = placement.unplace_state(als.als_train_placed(
                users, items, vals, N_USERS, N_ITEMS,
                placement=placement, rank=RANK, iterations=2, l2=0.1,
                seed=0))
        assert _rel(outs["ring"].user_factors,
                    outs["allgather"].user_factors) < 1e-5

    def test_legacy_sharded_entry_still_host_shaped(self, monkeypatch):
        """als_train_sharded keeps its historical contract: true-size
        host-shaped factors (now via the placement wrapper)."""
        users, items, vals = _data()
        state = als.als_train_sharded(
            users, items, vals, N_USERS, N_ITEMS, _mesh(2),
            rank=RANK, iterations=2, l2=0.1, seed=0)
        assert state.user_factors.shape == (N_USERS, RANK)
        assert state.item_factors.shape == (N_ITEMS, RANK)
        assert state.placement is None


# ---------------------------------------------------------------------------
# continuation retrain under a placement
# ---------------------------------------------------------------------------

def _tail_data():
    """Base COO + a tail shaped for the splice fast path: 8 touched
    rows that KEEP their padded width class (entries land in their
    existing slots) and 2 brand-new rows (degree 0 → delta buckets),
    comfortably under apply_tail's compaction bound."""
    rng = np.random.default_rng(0)
    users = rng.integers(0, N_USERS - 2, NNZ).astype(np.int32)
    items = rng.integers(0, N_ITEMS, NNZ).astype(np.int32)
    vals = rng.uniform(1, 5, NNZ).astype(np.float32)
    deg = np.bincount(users, minlength=N_USERS)
    widths = np.maximum(8, np.exp2(np.ceil(
        np.log2(np.maximum(deg, 1)))).astype(np.int64))
    stay = np.where((deg > 0) & (deg < widths))[0][:8].astype(np.int32)
    assert len(stay) == 8
    tu = np.concatenate([stay, np.repeat(
        np.asarray([N_USERS - 2, N_USERS - 1], np.int32), 5)])
    trng = np.random.default_rng(99)
    ti = trng.integers(0, N_ITEMS, len(tu)).astype(np.int32)
    tv = trng.uniform(1, 5, len(tu)).astype(np.float32)
    return ((users, items, vals),
            (np.concatenate([users, tu]), np.concatenate([items, ti]),
             np.concatenate([vals, tv])))


class TestPlacedRetrain:

    def _prev(self, base):
        state, _ = als.als_train(
            *base, n_users=N_USERS, n_items=N_ITEMS, rank=RANK,
            iterations=2, l2=0.1, seed=0)
        return als.ALSState(
            user_factors=np.asarray(state.user_factors),
            item_factors=np.asarray(state.item_factors))

    def test_placed_retrain_matches_single_chip(self):
        base, full = _tail_data()
        prev = self._prev(base)
        ref = retrain.als_retrain(
            *full, N_USERS, N_ITEMS, rank=RANK, iterations=3, l2=0.1,
            seed=0, prev_state=prev, tol=0.0)
        placement = make_placement(_mesh(4), N_USERS, N_ITEMS)
        stats: dict = {}
        got = retrain.als_retrain(
            *full, N_USERS, N_ITEMS, rank=RANK, iterations=3, l2=0.1,
            seed=0, prev_state=prev, tol=0.0, placement=placement,
            stats=stats)
        assert got.placement is placement
        assert stats["mode"] == "continue"
        got = placement.unplace_state(got)
        assert _rel(got.user_factors, ref.user_factors) < 1e-5
        assert _rel(got.item_factors, ref.item_factors) < 1e-5

    def test_placed_retrain_ring_fallback_parity(self, monkeypatch):
        """When the gather strategy resolves RING (table too wide to
        all-gather — the scale sharding exists for), the retrain must
        NOT fall back to full-table replication via the allgather-only
        splice plan: it preps fresh ring-layout sides, keeps the
        continuation warm start, stays one dispatch, and matches the
        single-chip retrain."""
        monkeypatch.setenv("PIO_SHARD_GATHER", "ring")
        base, full = _tail_data()
        prev = self._prev(base)
        ref = retrain.als_retrain(
            *full, N_USERS, N_ITEMS, rank=RANK, iterations=3, l2=0.1,
            seed=0, prev_state=prev, tol=0.0)
        placement = make_placement(_mesh(4), N_USERS, N_ITEMS)
        stats: dict = {}
        got = retrain.als_retrain(
            *full, N_USERS, N_ITEMS, rank=RANK, iterations=3, l2=0.1,
            seed=0, prev_state=prev, tol=0.0, placement=placement,
            plan_key="ring-retrain", stats=stats)
        assert stats["prep_plan"] == "ring-fresh"
        assert stats["mode"] == "continue"
        assert stats["train_dispatches"] == 1
        assert stats["one_dispatch"] is True
        got = placement.unplace_state(got)
        assert _rel(got.user_factors, ref.user_factors) < 1e-5
        assert _rel(got.item_factors, ref.item_factors) < 1e-5

    def test_placed_splice_one_dispatch_and_parity(self):
        """Same-geometry steady state: the O(delta) splice scatters run
        INSIDE the training jit — plan reused, train_dispatches == 1 —
        and the spliced result matches the fresh-prep result."""
        base, full = _tail_data()
        prev = self._prev(base)
        placement = make_placement(_mesh(4), N_USERS, N_ITEMS)

        def run(plan_key, seed_plan, stats):
            if seed_plan:
                retrain.drop_plans()
                retrain.prepare_with_reuse(
                    *base, N_USERS, N_ITEMS, plan_key=plan_key,
                    placement=placement)
            return retrain.als_retrain(
                *full, N_USERS, N_ITEMS, rank=RANK, iterations=3,
                l2=0.1, seed=0, prev_state=prev, tol=0.0,
                placement=placement, plan_key=plan_key, stats=stats)

        fresh_stats: dict = {}
        fresh = placement.unplace_state(
            run(None, seed_plan=False, stats=fresh_stats))
        spliced_stats: dict = {}
        spliced = placement.unplace_state(
            run("shard-splice", seed_plan=True, stats=spliced_stats))
        assert spliced_stats["prep_plan"] == "reused"
        # acceptance: one device dispatch per shard group, under
        # sharding exactly as on one chip
        assert spliced_stats["train_dispatches"] == 1
        assert spliced_stats["one_dispatch"] is True
        assert _rel(spliced.user_factors, fresh.user_factors) < 1e-5
        assert _rel(spliced.item_factors, fresh.item_factors) < 1e-5

    def test_reshard_invalidates_plan_and_stays_correct(self):
        """A live plan built at one mesh shape must NOT be spliced into
        at another: the placement key invalidates, the plan rebuilds
        once, and the factors still match the single-chip retrain."""
        base, full = _tail_data()
        prev = self._prev(base)
        p2 = make_placement(_mesh(2), N_USERS, N_ITEMS)
        p4 = make_placement(_mesh(4), N_USERS, N_ITEMS)
        assert p2.cache_key() != p4.cache_key()
        retrain.prepare_with_reuse(
            *base, N_USERS, N_ITEMS, plan_key="reshard", placement=p2)
        ref = retrain.als_retrain(
            *full, N_USERS, N_ITEMS, rank=RANK, iterations=3, l2=0.1,
            seed=0, prev_state=prev, tol=0.0)
        stats: dict = {}
        got = retrain.als_retrain(
            *full, N_USERS, N_ITEMS, rank=RANK, iterations=3, l2=0.1,
            seed=0, prev_state=prev, tol=0.0, placement=p4,
            plan_key="reshard", stats=stats)
        assert stats["prep_plan"] != "reused"
        got = p4.unplace_state(got)
        assert _rel(got.user_factors, ref.user_factors) < 1e-5
        assert _rel(got.item_factors, ref.item_factors) < 1e-5

    def test_place_state_redistributes_across_mesh_shapes(self):
        """continue_state's placement leg: a state placed at mesh shape
        A re-places under mesh shape B with the true-size prefix intact
        (the continuation-after-reshard seed path)."""
        users, items, vals = _data()
        p2 = make_placement(_mesh(2), N_USERS, N_ITEMS)
        at2 = als.als_train_placed(
            users, items, vals, N_USERS, N_ITEMS, placement=p2,
            rank=RANK, iterations=2, l2=0.1, seed=0)
        p8 = make_placement(_mesh(8), N_USERS, N_ITEMS)
        at8 = p8.place_state(at2)
        assert at8.placement is p8
        assert at8.user_factors.shape[0] == p8.n_users_padded
        np.testing.assert_array_equal(
            np.asarray(at8.user_factors)[:N_USERS],
            np.asarray(at2.user_factors)[:N_USERS])

    def test_grow_capacity_keeps_geometry_stable(self):
        """make_placement(grow=True) pow2-pads per-shard rows: ids
        appending within capacity keep the cache key AND placement
        equality/hash — the actual jit static-arg key, so steady-state
        retrains never recompile — while crossing capacity doubles."""
        mesh = _mesh(4)
        a = make_placement(mesh, 100, 60, grow=True)
        b = make_placement(mesh, 101, 61, grow=True)
        assert a.cache_key() == b.cache_key()
        assert a == b and hash(a) == hash(b)
        c = make_placement(mesh, 2 * a.n_users_padded, 60, grow=True)
        assert c.cache_key() != a.cache_key()
        assert c != a


# ---------------------------------------------------------------------------
# fold-in on a sharded frozen table
# ---------------------------------------------------------------------------

class TestShardedFoldIn:

    @pytest.mark.parametrize("implicit", [False, True])
    def test_foldin_matches_replicated_solver(self, implicit):
        rng = np.random.default_rng(3)
        M, K = 64, 8
        table = rng.normal(0, 0.3, (M, K)).astype(np.float32)
        placement = make_placement(_mesh(4), 32, M)
        placed = placement.place_table(table, "item")[:M]
        assert is_distributed(placed)
        ref_solver = FoldInSolver(table, l2=0.05, implicit=implicit,
                                  alpha=2.0)
        sharded = FoldInSolver(placed, l2=0.05, implicit=implicit,
                               alpha=2.0)
        assert sharded.sharded
        assert not sharded.use_kernel  # pallas never auto-partitions
        rows = []
        for d in (1, 7, 8, 33, 128):  # every ladder bucket class
            cols = rng.integers(0, M, d).astype(np.int32)
            vals = np.abs(rng.normal(2.0, 0.8, d)).astype(np.float32)
            rows.append((cols, vals))
        got = sharded.solve(rows)
        ref = ref_solver.solve(rows)
        for g, r in zip(got, ref):
            assert _rel(g, r) < 1e-4


# ---------------------------------------------------------------------------
# sharded serving: partial top-k + all-gather merge
# ---------------------------------------------------------------------------

class TestShardedTopK:

    def _placed_items(self, n_shards, n_items=N_ITEMS, k=RANK, seed=5):
        rng = np.random.default_rng(seed)
        table = rng.normal(0, 1.0, (n_items, k)).astype(np.float32)
        placement = make_placement(_mesh(n_shards), 16, n_items)
        return table, placement.place_table(table, "item"), placement

    def test_planted_merge_equivalence(self):
        """Per-shard partial top-k + merge ≡ dense ranking, with planted
        winners scattered across every shard's slice."""
        table, placed, placement = self._placed_items(8)
        rng = np.random.default_rng(6)
        uv = rng.normal(0, 1.0, RANK).astype(np.float32)
        # plant extreme winners on specific shards (incl. the last)
        winners = [1, 11, 21, 36]
        for w, boost in zip(winners, (40.0, 30.0, 20.0, 10.0)):
            table[w] = boost * uv / np.linalg.norm(uv) ** 2
        placed = placement.place_table(table, "item")
        out = np.asarray(topk.sharded_top_k(
            jnp.asarray(uv), placed, 10,
            valid_items=placement.n_items))
        ref_scores = table @ uv
        ref_ids = np.argsort(-ref_scores)[:10]
        assert list(out[1].astype(int)[:4]) == winners
        assert set(out[1].astype(int)) == set(ref_ids)
        np.testing.assert_allclose(
            out[0], np.sort(ref_scores)[::-1][:10], rtol=1e-5)

    def test_padding_rows_never_served(self):
        """Placement padding rows hold zero factors — without the
        valid_items mask they would outrank genuinely negative items."""
        rng = np.random.default_rng(7)
        table = rng.normal(0, 1.0, (N_ITEMS, RANK)).astype(np.float32)
        placement = make_placement(_mesh(8), 16, N_ITEMS)
        placed = placement.place_table(table, "item")
        assert placement.n_items_padded > N_ITEMS
        uv = rng.normal(0, 1.0, RANK).astype(np.float32)
        out = np.asarray(topk.sharded_top_k(
            jnp.asarray(uv), placed, placement.n_items,
            valid_items=placement.n_items))
        ids = set(out[1].astype(int))
        assert all(i < N_ITEMS for i in ids)

    def test_exclude_and_allowed_mask(self):
        table, placed, placement = self._placed_items(4)
        rng = np.random.default_rng(8)
        uv = rng.normal(0, 1.0, RANK).astype(np.float32)
        scores = table @ uv
        order = np.argsort(-scores)
        exclude = order[:3].astype(np.int32)         # knock out the top 3
        allowed = np.ones(N_ITEMS, bool)
        allowed[order[3]] = False                    # ... and the 4th
        out = np.asarray(topk.sharded_top_k(
            jnp.asarray(uv), placed, 5, exclude=jnp.asarray(exclude),
            allowed_mask=jnp.asarray(allowed),
            valid_items=placement.n_items))
        assert list(out[1].astype(int)) == list(order[4:9])

    def test_serving_entry_auto_routes_distributed(self):
        """score_and_top_k / score_user_and_top_k detect an actually-
        distributed item table and take the sharded merge path; with
        ``valid_items`` the padding tail is masked and the result
        matches the replicated entry exactly — padding ids are NEVER
        servable (the make_placement contract)."""
        table, placed, placement = self._placed_items(4)
        rng = np.random.default_rng(9)
        uv = rng.normal(0, 1.0, RANK).astype(np.float32)
        got = np.asarray(topk.score_and_top_k(
            jnp.asarray(uv), placed, 5, valid_items=N_ITEMS))
        ref = np.asarray(topk.score_and_top_k(
            jnp.asarray(uv), jnp.asarray(table), 5))
        assert (got[1] < N_ITEMS).all()
        assert set(got[1].astype(int)) == set(ref[1].astype(int))
        uf = rng.normal(0, 1.0, (16, RANK)).astype(np.float32)
        got_u = np.asarray(topk.score_user_and_top_k(
            jnp.asarray(uf), placed, jnp.asarray(3), 5,
            valid_items=N_ITEMS))
        ref_u = np.asarray(topk.score_user_and_top_k(
            jnp.asarray(uf), jnp.asarray(table), jnp.asarray(3), 5))
        assert (got_u[1] < N_ITEMS).all()
        assert set(got_u[1].astype(int)) == set(ref_u[1].astype(int))

    def test_batch_topk_valid_items_masks_padding(self):
        rng = np.random.default_rng(10)
        uf = rng.normal(0, 1.0, (8, RANK)).astype(np.float32)
        items = rng.normal(-1.0, 0.2, (40, RANK)).astype(np.float32)
        items[N_ITEMS:] = 0.0  # placement-style zero padding
        out = np.asarray(topk.batch_score_top_k(
            jnp.asarray(uf), jnp.asarray(items),
            np.arange(8, dtype=np.int32), 10, valid_items=N_ITEMS))
        assert (out[1] < N_ITEMS).all()


# ---------------------------------------------------------------------------
# seams: forced device count, context gating, shard telemetry
# ---------------------------------------------------------------------------

class TestSeams:

    def test_pio_mesh_devices_caps_standard_mesh(self, monkeypatch):
        _need(4)
        from incubator_predictionio_tpu.parallel import mesh as pmesh

        monkeypatch.setenv("PIO_MESH_DEVICES", "4")
        assert pmesh.device_count() == 4
        assert make_mesh().devices.size == 4
        monkeypatch.setenv("PIO_MESH_DEVICES", "junk")
        assert pmesh.forced_device_count() is None

    def test_placement_for_ctx_gating(self, monkeypatch):
        _need(2)

        class Ctx:
            model_parallelism = 1
            mesh = None

        monkeypatch.delenv("PIO_SHARD_TABLES", raising=False)
        assert placement_for_ctx(Ctx(), 10, 10) is None
        monkeypatch.setenv("PIO_SHARD_TABLES", "1")
        p = placement_for_ctx(Ctx(), 10, 10)
        assert isinstance(p, FactorPlacement)
        # grow policy: per-shard capacity is pow2 → stable geometry
        assert p.users_capacity % p.n_shards == 0
        # the gate honors the PIO_MESH_DEVICES cap: a capped 1-device
        # mesh is the single-chip path, whatever jax.device_count() is
        monkeypatch.setenv("PIO_MESH_DEVICES", "1")
        assert placement_for_ctx(Ctx(), 10, 10) is None

    def test_shard_metrics_booked(self, monkeypatch):
        users, items, vals = _data()
        monkeypatch.setenv("PIO_SHARD_GATHER", "allgather")
        placement = make_placement(_mesh(2), N_USERS, N_ITEMS)
        before = obs_metrics.REGISTRY.get("pio_shard_gather_bytes_total")
        before = (before.labels(strategy="allgather").value
                  if before is not None else 0.0)
        als.als_train_placed(
            users, items, vals, N_USERS, N_ITEMS, placement=placement,
            rank=RANK, iterations=2, l2=0.1, seed=0)
        assert obs_metrics.REGISTRY.get(
            "pio_shard_mesh_devices").value == 2
        rows = obs_metrics.REGISTRY.get("pio_shard_rows")
        assert rows.labels(side="user").value == placement.shard_rows(
            "user")
        assert rows.labels(side="item").value == placement.shard_rows(
            "item")
        after = obs_metrics.REGISTRY.get(
            "pio_shard_gather_bytes_total").labels(
                strategy="allgather").value
        # 2 sweeps × both half-sweeps' analytic all-gather volume
        expect = (placement.allgather_bytes("item", 2, RANK)
                  + placement.allgather_bytes("user", 2, RANK))
        assert after - before == expect


# ---------------------------------------------------------------------------
# ring host prep: vectorized builder parity + ring-plan reuse
# ---------------------------------------------------------------------------

class TestRingLayout:
    """The vectorized ``build_ring_side`` (numpy bucketing, no
    per-(row, step) Python loop — ROADMAP item 1's flagged host cost)
    must be BITWISE-identical to the loop reference it replaced, and
    the ring-plan cache must let a ring-mode continuation retrain skip
    the full-COO prep without moving the trained factors."""

    @pytest.mark.parametrize("seed,mw", [(0, 4), (1, 13), (2, 64),
                                         (3, 4), (4, 16)])
    def test_vectorized_matches_loop_bitwise(self, seed, mw):
        from incubator_predictionio_tpu.parallel import sharding

        rng = np.random.default_rng(seed)
        n = int(rng.choice([2, 4, 8]))
        sr_s = int(rng.integers(4, 24))
        sr_o = int(rng.integers(4, 24))
        nnz = int(rng.integers(40, 3000))
        rows = rng.integers(0, n * sr_s, nnz)
        cols = rng.integers(0, n * sr_o, nnz)
        vals = rng.normal(size=nnz).astype(np.float32)
        a = sharding.build_ring_side(rows, cols, vals, n, sr_s, sr_o,
                                     max_width=mw)
        b = sharding.build_ring_side_reference(
            rows, cols, vals, n, sr_s, sr_o, max_width=mw)
        assert len(a[0]) == len(b[0])
        for cls_a, cls_b in zip(a[0], b[0]):
            for xa, xb in zip(cls_a, cls_b):
                assert xa.dtype == xb.dtype
                assert xa.shape == xb.shape
                assert np.array_equal(xa, xb)
        assert (a[1] is None) == (b[1] is None)
        if a[1] is not None:
            for xa, xb in zip(a[1], b[1]):
                assert xa.dtype == xb.dtype
                assert xa.shape == xb.shape
                assert np.array_equal(xa, xb)

    def test_empty_input(self):
        from incubator_predictionio_tpu.parallel import sharding

        pure, mixed = sharding.build_ring_side(
            np.zeros(0, np.int64), np.zeros(0, np.int64),
            np.zeros(0, np.float32), 4, 8, 8)
        assert pure == () and mixed is None

    def test_ring_plan_reuse_retrain_parity(self, monkeypatch):
        """Second ring-mode retrain with the same plan key splices the
        tail into the resident host layout (``prep_plan ==
        "ring-reused"``) and trains to the same factors as a
        fresh-prepped ring retrain."""
        monkeypatch.setenv("PIO_SHARD_GATHER", "ring")
        base, full = _tail_data()
        state, _ = als.als_train(
            *base, n_users=N_USERS, n_items=N_ITEMS, rank=RANK,
            iterations=2, l2=0.1, seed=0)
        prev = als.ALSState(
            user_factors=np.asarray(state.user_factors),
            item_factors=np.asarray(state.item_factors))
        placement = make_placement(_mesh(4), N_USERS, N_ITEMS)
        s1: dict = {}
        retrain.als_retrain(
            *base, N_USERS, N_ITEMS, rank=RANK, iterations=3, l2=0.1,
            seed=0, prev_state=prev, tol=0.0, placement=placement,
            plan_key="ring-reuse", stats=s1)
        assert s1["prep_plan"] == "ring-fresh"
        s2: dict = {}
        got = retrain.als_retrain(
            *full, N_USERS, N_ITEMS, rank=RANK, iterations=3, l2=0.1,
            seed=0, prev_state=prev, tol=0.0, placement=placement,
            plan_key="ring-reuse", stats=s2)
        assert s2["prep_plan"] == "ring-reused"
        assert s2["prep_delta_rows"] == len(full[0]) - len(base[0])
        assert s2["train_dispatches"] == 1
        retrain.drop_plans()
        s3: dict = {}
        ref = retrain.als_retrain(
            *full, N_USERS, N_ITEMS, rank=RANK, iterations=3, l2=0.1,
            seed=0, prev_state=prev, tol=0.0, placement=placement,
            plan_key="ring-fresh-key", stats=s3)
        assert s3["prep_plan"] == "ring-fresh"
        got = placement.unplace_state(got)
        ref = placement.unplace_state(ref)
        assert _rel(got.user_factors, ref.user_factors) < 1e-5
        assert _rel(got.item_factors, ref.item_factors) < 1e-5

    def test_ring_plan_invalidates_on_reshard(self, monkeypatch):
        """A retrain at a different mesh shape must NOT splice into a
        stale geometry's layout — the plan invalidates, rebuilds fresh,
        and stays correct."""
        monkeypatch.setenv("PIO_SHARD_GATHER", "ring")
        base, full = _tail_data()
        p4 = make_placement(_mesh(4), N_USERS, N_ITEMS)
        p2 = make_placement(_mesh(2), N_USERS, N_ITEMS)
        s1: dict = {}
        retrain.als_retrain(
            *base, N_USERS, N_ITEMS, rank=RANK, iterations=2, l2=0.1,
            seed=0, tol=0.0, placement=p4, plan_key="ring-shape",
            stats=s1)
        s2: dict = {}
        got = retrain.als_retrain(
            *full, N_USERS, N_ITEMS, rank=RANK, iterations=2, l2=0.1,
            seed=0, tol=0.0, placement=p2, plan_key="ring-shape",
            stats=s2)
        assert s2["prep_plan"] == "ring-fresh"
        retrain.drop_plans()
        ref = retrain.als_retrain(
            *full, N_USERS, N_ITEMS, rank=RANK, iterations=2, l2=0.1,
            seed=0, tol=0.0, placement=p2, plan_key="other", stats={})
        got = p2.unplace_state(got)
        ref = p2.unplace_state(ref)
        assert _rel(got.user_factors, ref.user_factors) < 1e-5
