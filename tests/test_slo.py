"""SLO burn-rate engine + freshness tracer + dispatch profiler.

Pins the three new measurement surfaces:

- burn-rate math on planted good/bad observation streams (histogram and
  gauge objectives, fast/slow windows, budget remaining, the breach
  flip) on a FRESH registry with a fake clock — no sleeps;
- ``GET /slo`` end to end on the admin and dashboard servers, including
  the planted-breach flip the autonomous controller will key on;
- the end-to-end freshness tracker's stage accounting (append → poll →
  fold → first serve) with planted wall clocks, the backfill guard, and
  the linked span chain;
- the PIO_PROFILE dispatch profiler: off-by-default free path, on-path
  attribution and MFU math, and the admin ``POST /profile`` validation
  (400/409).
"""

import json
import logging
import urllib.error
import urllib.request

import numpy as np
import pytest

from incubator_predictionio_tpu.obs import freshness as obs_freshness
from incubator_predictionio_tpu.obs import metrics as obs_metrics
from incubator_predictionio_tpu.obs import profile as obs_profile
from incubator_predictionio_tpu.obs import slo as obs_slo
from incubator_predictionio_tpu.obs.metrics import Registry
from incubator_predictionio_tpu.obs.slo import SLOEngine, SLOSpec
from incubator_predictionio_tpu.utils import times
from incubator_predictionio_tpu.utils.times import FakeClock


# ---------------------------------------------------------------------------
# engine unit behavior (fresh registry, fake clock)
# ---------------------------------------------------------------------------

def make_engine(reg, clock, target=0.99, threshold=1.0, kind="histogram",
                metric="t_slo_seconds"):
    spec = SLOSpec(name="t", metric=metric, threshold=threshold,
                   target=target, kind=kind)
    return SLOEngine(specs=(spec,), registry=reg, clock=clock,
                     fast_window_s=60.0, slow_window_s=600.0,
                     min_tick_interval_s=0.0)


def test_burn_rate_zero_when_healthy_then_flips_on_breach():
    reg = Registry()
    clock = FakeClock()
    h = reg.histogram("t_slo_seconds", "x", buckets=(1.0, 2.0))
    eng = make_engine(reg, clock)
    h.observe(0.5, 100)                      # 100 good
    eng.tick(force=True)
    clock.advance(10)
    out = eng.evaluate()[0]
    assert out["noData"] is False
    assert out["windows"]["fast"]["burnRate"] == 0.0
    assert out["errorBudgetRemaining"] == 1.0
    assert out["breached"] is False
    # plant the breach: 50 observations past the threshold
    h.observe(5.0, 50)
    clock.advance(10)
    out = eng.evaluate()[0]
    # bad fraction 50/150 over the window, allowed 1% -> burn >> 1
    assert out["windows"]["fast"]["burnRate"] > 1.0
    assert out["breached"] is True
    assert out["errorBudgetRemaining"] < 1.0


def test_threshold_rounds_down_to_bucket_bound():
    """A threshold between bounds must not overstate the good count —
    cumulative_below rounds DOWN (flag early, never late)."""
    reg = Registry()
    h = reg.histogram("t_r_seconds", "x", buckets=(1.0, 2.0, 4.0))
    h.observe(1.5)   # in the le=2.0 bucket
    below, total = h.cumulative_below(3.0)   # between 2.0 and 4.0
    assert (below, total) == (1, 1)
    below, _ = h.cumulative_below(1.2)       # between 1.0 and 2.0
    assert below == 0                        # the 1.5 obs is NOT granted


def test_gauge_slo_counts_one_observation_per_tick():
    reg = Registry()
    clock = FakeClock()
    g = reg.gauge("t_stale_seconds", "x")
    eng = make_engine(reg, clock, kind="gauge", metric="t_stale_seconds",
                      threshold=100.0)
    g.set(10.0)
    eng.tick(force=True)
    clock.advance(5)
    out = eng.evaluate()[0]
    assert out["windows"]["fast"]["burnRate"] == 0.0
    g.set(5000.0)                            # over the staleness bound
    for _ in range(20):
        clock.advance(1)
        eng.tick(force=True)
    out = eng.evaluate()[0]
    assert out["windows"]["fast"]["burnRate"] > 1.0
    assert out["breached"] is True


def test_missing_metric_reports_no_data_not_breach():
    reg = Registry()
    eng = make_engine(reg, FakeClock())
    out = eng.evaluate()[0]
    assert out["noData"] is True
    assert out["breached"] is False
    assert out["errorBudgetRemaining"] == 1.0


def test_registered_but_never_set_gauge_is_no_data():
    """A gauge REGISTERED at import but never populated (deploy failed,
    no model serving) must not tick healthy observations — 0.0-by-
    default would report a green staleness budget while nothing is
    being measured."""
    reg = Registry()
    clock = FakeClock()
    g = reg.gauge("t_unset_seconds", "x")
    eng = make_engine(reg, clock, kind="gauge",
                      metric="t_unset_seconds", threshold=100.0)
    eng.tick(force=True)
    clock.advance(5)
    out = eng.evaluate()[0]
    assert out["noData"] is True
    assert out["breached"] is False
    g.set(0.0)   # a genuine zero IS data
    clock.advance(5)
    out = eng.evaluate()[0]
    assert out["noData"] is False


def test_slow_window_confirms_sustained_burn():
    reg = Registry()
    clock = FakeClock()
    h = reg.histogram("t_slo_seconds", "x", buckets=(1.0,))
    eng = make_engine(reg, clock)
    eng.tick(force=True)
    # a transient burst of bad, then a long healthy stretch
    h.observe(5.0, 10)
    clock.advance(30)
    eng.tick(force=True)
    h.observe(0.5, 10_000)
    clock.advance(500)
    out = eng.evaluate()[0]
    # the fast window (60 s) no longer covers the burst; the slow one
    # still does but diluted by the healthy traffic
    assert out["windows"]["fast"]["burnRate"] == 0.0
    assert 0.0 < out["windows"]["slow"]["burnRate"] < 1.0


def test_exported_gauges_update_at_evaluate():
    reg = obs_metrics.REGISTRY
    clock = FakeClock()
    h = reg.histogram("t_exp_seconds", "x", buckets=(1.0,))
    spec = SLOSpec(name="t_exp", metric="t_exp_seconds", threshold=1.0,
                   target=0.9)
    eng = SLOEngine(specs=(spec,), registry=reg, clock=clock,
                    min_tick_interval_s=0.0)
    h.observe(9.0, 10)
    eng.tick(force=True)
    clock.advance(10)
    h.observe(9.0, 10)
    eng.evaluate()
    assert obs_slo.BURN_RATE.labels(slo="t_exp", window="fast").value > 1.0
    assert obs_slo.BUDGET_REMAINING.labels(slo="t_exp").value < 1.0


def test_counter_reset_clamps_process_mode():
    """A worker restart mid-window zeroes its cumulative counters. The
    snapshot ring's window delta must CLAMP at zero — a head snapshot
    below the base must never become negative good/bad deltas (negative
    burn, or a breach computed from nonsense fractions)."""
    reg = Registry()
    clock = FakeClock()
    h = reg.histogram("t_reset_seconds", "x", buckets=(1.0, 2.0))
    eng = make_engine(reg, clock, metric="t_reset_seconds")
    eng.tick(force=True)                     # zero baseline snapshot
    h.observe(5.0, 100)                      # 100 bad pre-restart
    clock.advance(10)
    out = eng.evaluate()[0]
    assert out["windows"]["fast"]["burnRate"] > 1.0
    # the restart: a fresh process re-registers the family from zero
    # and has seen LESS traffic than the old cumulative counts
    reg2 = Registry()
    h2 = reg2.histogram("t_reset_seconds", "x", buckets=(1.0, 2.0))
    h2.observe(0.5, 10)                      # 10 good, post-restart
    eng.registry = reg2
    clock.advance(10)
    out = eng.evaluate()[0]
    for w in ("fast", "slow"):
        win = out["windows"][w]
        assert win["burnRate"] >= 0.0, win
        assert win["badFraction"] >= 0.0, win
        assert win["observations"] >= 0, win
    # the clamped window sees no NEW bad observations (the 100 old bad
    # must not re-count, and certainly not count negatively)
    assert out["windows"]["fast"]["burnRate"] == 0.0
    assert 0.0 <= out["errorBudgetRemaining"] <= 1.0


class _ShrinkingFleet:
    """Registry-shaped fleet stub whose histogram family RESETS between
    reads (a worker restart between two controller/engine ticks):
    second and later reads report lower cumulative counts."""

    def __init__(self):
        self.reads = 0

    def get(self, name):
        from incubator_predictionio_tpu.obs import expofmt, federate

        self.reads += 1
        m = federate.FederatedMetric(name, "histogram")
        if self.reads == 1:
            child = expofmt.HistogramChild(
                buckets=[(1.0, 50.0), (2.0, 50.0)], sum=500.0,
                count=150.0)                 # 100 past the last bound
        else:
            # post-restart: counters re-grew from zero, still below
            # the pre-restart cumulative state
            child = expofmt.HistogramChild(
                buckets=[(1.0, 10.0), (2.0, 10.0)], sum=5.0,
                count=10.0)
        m.absorb("w0", expofmt.Family(
            name=name, kind="histogram",
            histograms={frozenset(): child}))
        return m


def test_counter_reset_clamps_fleet_mode():
    """Same clamp through the FEDERATED registry shape: a restarted
    worker's re-scraped exposition carries lower cumulative buckets,
    and the fleet engine's ring must clamp rather than emit negative
    burn (the fleet /slo the freshness controller keys on)."""
    clock = FakeClock()
    fleet = _ShrinkingFleet()
    spec = SLOSpec(name="t", metric="t_fleet_seconds", threshold=1.0,
                   target=0.99)
    eng = SLOEngine(specs=(spec,), registry=fleet, clock=clock,
                    fast_window_s=60.0, slow_window_s=600.0,
                    min_tick_interval_s=0.0, export_gauges=False)
    eng.tick(force=True)                     # sees 150 obs, 100 bad
    clock.advance(10)
    out = eng.evaluate()[0]                  # post-restart read: 10/0
    for w in ("fast", "slow"):
        win = out["windows"][w]
        assert win["burnRate"] >= 0.0, win
        assert win["badFraction"] >= 0.0, win
        assert win["observations"] >= 0, win
    assert out["windows"]["fast"]["burnRate"] == 0.0
    assert out["breached"] is False
    assert 0.0 <= out["errorBudgetRemaining"] <= 1.0


# ---------------------------------------------------------------------------
# GET /slo end to end (admin + dashboard), planted breach flip
# ---------------------------------------------------------------------------

@pytest.fixture
def slo_stack(monkeypatch):
    from incubator_predictionio_tpu.data.storage import Storage
    from incubator_predictionio_tpu.servers.admin import AdminServer
    from incubator_predictionio_tpu.servers.dashboard import DashboardServer

    Storage.configure({
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    })
    clock = FakeClock(1000.0)
    prev = times.set_monotonic(clock)
    obs_slo.reset_engine()
    ad = AdminServer(ip="127.0.0.1", port=0)
    db = DashboardServer(ip="127.0.0.1", port=0)
    ports = {"admin": ad.start_background(),
             "dashboard": db.start_background(), "clock": clock}
    try:
        yield ports
    finally:
        ad.stop()
        db.stop()
        times.set_monotonic(prev)
        obs_slo.reset_engine()
        Storage.reset()


def get_json(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30) as resp:
        assert resp.status == 200
        return json.loads(resp.read())


def test_slo_route_on_admin_and_dashboard(slo_stack):
    for name in ("admin", "dashboard"):
        body = get_json(slo_stack[name], "/slo")
        names = {s["name"] for s in body["slos"]}
        # the three shipped objectives are declared
        assert {"serve_p99", "freshness_p95", "staleness"} <= names
        for s in body["slos"]:
            assert "errorBudgetRemaining" in s
            assert set(s["windows"]) == {"fast", "slow"}
            assert "burnRate" in s["windows"]["fast"]
        assert body["windows"]["fastSeconds"] > 0


def test_slo_e2e_planted_breach_flips_burn_rate(slo_stack):
    """THE acceptance contract: plant an SLO breach and observe the
    burn-rate flip through GET /slo."""
    clock = slo_stack["clock"]
    qlat = obs_metrics.REGISTRY.histogram(
        "pio_query_latency_seconds",
        "per-query serving wall (micro-batch members share the batch "
        "wall)", labels=("tenant",)).labels(tenant="default")
    qlat.observe(0.001, 200)          # healthy traffic, under any bound
    body = get_json(slo_stack["admin"], "/slo")
    clock.advance(5)
    serve = [s for s in get_json(slo_stack["admin"], "/slo")["slos"]
             if s["name"] == "serve_p99"][0]
    assert serve["breached"] is False
    # the breach: a flood of queries far over the 0.25 s objective
    qlat.observe(10.0, 500)
    clock.advance(5)
    serve = [s for s in get_json(slo_stack["admin"], "/slo")["slos"]
             if s["name"] == "serve_p99"][0]
    assert serve["windows"]["fast"]["burnRate"] > 1.0
    assert serve["breached"] is True
    assert serve["errorBudgetRemaining"] < 1.0
    # the exported gauges flipped too (what the controller will scrape)
    assert obs_slo.BURN_RATE.labels(
        slo="serve_p99", window="fast").value > 1.0


def test_slo_scrape_collector_refreshes_gauges(slo_stack):
    """/metrics drives the engine via the registry collector."""
    with urllib.request.urlopen(
            f"http://127.0.0.1:{slo_stack['admin']}/metrics",
            timeout=30) as resp:
        text = resp.read().decode()
    assert "pio_slo_burn_rate" in text
    assert "pio_slo_error_budget_remaining" in text


# ---------------------------------------------------------------------------
# freshness tracker (planted wall clock — no sleeps)
# ---------------------------------------------------------------------------

@pytest.fixture
def wall():
    box = {"ms": 1_000_000}
    prev = times.set_wall_millis(lambda: box["ms"])
    yield box
    times.set_wall_millis(prev)


def test_freshness_stages_and_histogram(wall, caplog):
    tr = obs_freshness.FreshnessTracker(engine="t_fresh")
    hist = obs_freshness.FRESHNESS_SECONDS.labels(engine="t_fresh")
    before = hist.count
    with caplog.at_level(logging.INFO, logger="pio.trace"):
        tr.on_poll_batch({"u1": 1_000_000 - 2_000})  # appended 2 s ago
        tr.on_folded(["u1"], fold_wall_s=0.25)
        wall["ms"] += 500                            # 0.5 s to first hit
        tr.on_serve_hit("u1")
    assert hist.count == before + 1
    # freshness = 2.0 s (append -> poll) + 0.5 s (publish -> serve)
    assert hist.sum >= 2.4
    assert obs_freshness.POLL_LAG_SECONDS.labels(
        engine="t_fresh").value == pytest.approx(2.0)
    assert obs_freshness.FOLD_SECONDS.labels(
        engine="t_fresh").value == pytest.approx(0.25)
    assert obs_freshness.SERVE_PICKUP_SECONDS.labels(
        engine="t_fresh").value == pytest.approx(0.5)
    # the sampled journey emitted one linked span chain under ONE id
    spans = [json.loads(r.getMessage()) for r in caplog.records
             if r.name == "pio.trace"]
    chain = [s for s in spans if s["span"].startswith("speed.")]
    assert {s["span"] for s in chain} == {
        "speed.poll", "speed.foldin", "speed.serve"}
    assert len({s["traceId"] for s in chain}) == 1
    # a second hit on the same key books nothing further
    tr.on_serve_hit("u1")
    assert hist.count == before + 1


def test_freshness_buckets_resolve_minutes_scale():
    """The freshness histogram uses its own seconds-to-hours ladder —
    the serving-latency default caps at ~13 s and would saturate the
    headline metric exactly when freshness goes bad."""
    bounds = obs_freshness.FRESHNESS_BUCKETS
    assert max(bounds) > 3600.0          # a wedged poller still resolves
    assert min(bounds) <= 0.05           # a hot loop still resolves
    h = obs_freshness.FRESHNESS_SECONDS.labels(engine="t_buckets")
    h.observe(300.0)                     # five minutes stale
    assert h.quantile(0.5) == pytest.approx(300.0, rel=0.7)
    assert h.quantile(0.5) > 13.2        # NOT clamped at the old cap


def test_cpplog_count_marks_never_understate(tmp_path, wall):
    """The count-observation stamp rule: a tail [lo, hi) is bounded by
    the NEWEST observation with count <= lo (every entry past lo was
    appended after that wall — age only ever overstated). Entries that
    predate every observation report -1 instead of borrowing a later
    wall, which would fabricate freshness."""
    cpplog = pytest.importorskip(
        "incubator_predictionio_tpu.data.storage.cpplog")
    from incubator_predictionio_tpu.data.storage import StorageClientConfig

    try:
        client = cpplog.StorageClient(
            StorageClientConfig(properties={"PATH": str(tmp_path)}))
    except Exception:
        pytest.skip("native library unavailable")
    try:
        path = tmp_path / "t.log"
        with client.lock:
            # no observations at all: unattributable
            assert client.append_wall_since_locked(path, 0) == -1
            wall["ms"] = 1_000
            client.note_count_locked(path, 10)
            wall["ms"] = 2_000
            client.note_count_locked(path, 20)
            # entries >= 10 were appended after the count-10 observation
            assert client.append_wall_since_locked(path, 10) == 1_000
            assert client.append_wall_since_locked(path, 15) == 1_000
            # entries >= 20 appended after the newer observation
            assert client.append_wall_since_locked(path, 20) == 2_000
            assert client.append_wall_since_locked(path, 25) == 2_000
            # entries 0..9 predate every known wall: never borrow one
            assert client.append_wall_since_locked(path, 0) == -1
            assert client.append_wall_since_locked(path, 9) == -1
            # re-observing the same count later TIGHTENS the bound
            wall["ms"] = 3_000
            client.note_count_locked(path, 20)
            assert client.append_wall_since_locked(path, 25) == 3_000
    finally:
        client.close()


def test_freshness_skips_historical_backfill(wall):
    tr = obs_freshness.FreshnessTracker(engine="t_backfill")
    hist = obs_freshness.FRESHNESS_SECONDS.labels(engine="t_backfill")
    year_ms = 365 * 24 * 3600 * 1000
    tr.on_poll_batch({"old": 1_000_000 - year_ms, "unknown": -1})
    tr.on_folded(["old", "unknown"], 0.1)
    tr.on_serve_hit("old")
    tr.on_serve_hit("unknown")
    assert hist.count == 0


def test_freshness_discard_and_invalidate(wall):
    tr = obs_freshness.FreshnessTracker(engine="t_disc")
    tr.on_poll_batch({"u1": 999_000, "u2": 999_000})
    tr.discard(["u1"])
    assert tr.stats()["pendingAppend"] == 1
    tr.invalidate()
    assert tr.stats() == {"pendingAppend": 0, "awaitingServe": 0}


def test_overlay_freshness_end_to_end(wall):
    """Through the real overlay: rate -> poll -> fold -> lookup hit
    books one pio_freshness_seconds observation."""
    from incubator_predictionio_tpu.data.datamap import DataMap
    from incubator_predictionio_tpu.data.event import Event
    from incubator_predictionio_tpu.data.storage import App, Storage
    from incubator_predictionio_tpu.data.store import EventStore
    from incubator_predictionio_tpu.speed.overlay import (
        SpeedOverlay,
        SpeedOverlayConfig,
    )
    from incubator_predictionio_tpu.utils.times import now_utc

    Storage.configure({
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    })
    try:
        Storage.get_meta_data_apps().insert(App(0, "freshapp"))
        rng = np.random.default_rng(0)
        other = rng.normal(0, 0.3, (5, 4)).astype(np.float32)
        overlay = SpeedOverlay(
            SpeedOverlayConfig(app_name="freshapp", engine="t_e2e",
                               event_names=("rate",),
                               value_prop="rating", l2=0.1),
            other_factors=other,
            other_index={f"i{k}": k for k in range(5)})
        hist = obs_freshness.FRESHNESS_SECONDS.labels(engine="t_e2e")
        before = hist.count
        EventStore.write([Event(
            event="rate", entity_type="user", entity_id="cold1",
            target_entity_type="item", target_entity_id="i2",
            properties=DataMap({"rating": 4.0}),
            event_time=now_utc())], "freshapp")
        wall["ms"] += 3_000                 # the poll runs 3 s later
        overlay.poll()
        wall["ms"] += 1_000                 # first query 1 s after fold
        assert overlay.lookup("cold1") is not None
        assert hist.count == before + 1
        # append -> serve spans the planted 4 s
        assert hist.sum >= 3.9
    finally:
        Storage.reset()


# ---------------------------------------------------------------------------
# dispatch profiler
# ---------------------------------------------------------------------------

def test_profiler_off_by_default(monkeypatch):
    monkeypatch.delenv("PIO_PROFILE", raising=False)
    assert obs_profile.enabled() is False
    assert obs_profile.t0() is None
    # record with a None start is the documented free no-op
    obs_profile.record(None, "train", "x", 1e9, object())


def test_profiler_attribution_and_mfu(monkeypatch):
    monkeypatch.setenv("PIO_PROFILE", "1")
    monkeypatch.setenv("PIO_BENCH_PEAK_FLOPS", "1e12")
    t0 = obs_profile.t0()
    assert t0 is not None
    obs_profile.record(t0, "t_phase", "t_op", 2e9)
    assert obs_profile.DEVICE_DISPATCHES.labels(op="t_op").value == 1
    assert obs_profile.DEVICE_FLOPS.labels(op="t_op").value == 2e9
    secs = obs_profile.DEVICE_SECONDS.labels(op="t_op").value
    assert secs > 0
    mfu = obs_profile.MFU.labels(phase="t_phase").value
    assert mfu == pytest.approx(2e9 / secs / 1e12, rel=1e-6)


def test_profiled_foldin_books_device_time(monkeypatch):
    from incubator_predictionio_tpu.speed.foldin import FoldInSolver

    monkeypatch.setenv("PIO_PROFILE", "1")
    rng = np.random.default_rng(0)
    other = rng.normal(0, 0.3, (20, 4)).astype(np.float32)
    solver = FoldInSolver(other, l2=0.1)
    before = obs_profile.DEVICE_DISPATCHES.labels(op="foldin_solve").value
    solver.solve([(np.asarray([1, 2], np.int32),
                   np.asarray([1.0, 2.0], np.float32))])
    assert obs_profile.DEVICE_DISPATCHES.labels(
        op="foldin_solve").value == before + 1
    assert obs_profile.MFU.labels(phase="foldin").value > 0


def test_train_flops_matches_bench_convention():
    from incubator_predictionio_tpu.ops import als

    f = als.train_flops(1000, 50, 40, 8, 4, 0)
    assert f > 0
    # linear in sweeps and at least linear in nnz
    assert als.train_flops(1000, 50, 40, 8, 8, 0) == pytest.approx(2 * f)
    assert als.train_flops(2000, 50, 40, 8, 4, 0) > f


def test_fused_train_books_under_its_own_op_label(monkeypatch):
    """Kernel-path training attributes under op="als_fused", the XLA
    assembly under op="als_train" — separate trajectories in /metrics —
    while both book the SAME als.train_flops formula, so
    pio_mfu{phase="train"} stays comparable across the split (the
    bench's obs_mfu_train cross-check relies on it)."""
    from incubator_predictionio_tpu.ops import als

    monkeypatch.setenv("PIO_PROFILE", "1")
    rng = np.random.default_rng(3)
    users = rng.integers(0, 24, 400).astype(np.int32)
    items = rng.integers(0, 16, 400).astype(np.int32)
    ratings = rng.normal(3.5, 1.0, 400).astype(np.float32)
    kw = dict(n_users=24, n_items=16, rank=4, iterations=2, l2=0.1)

    def booked(op):
        return (obs_profile.DEVICE_DISPATCHES.labels(op=op).value,
                obs_profile.DEVICE_FLOPS.labels(op=op).value)

    monkeypatch.setattr(als, "_ALS_KERNEL", "off")
    d0, f0 = booked("als_train")
    als.als_train(users, items, ratings, **kw)
    d1, f1 = booked("als_train")
    assert d1 == d0 + 1 and f1 > f0

    monkeypatch.setattr(als, "_ALS_KERNEL", "on")
    monkeypatch.setattr(als, "_KERNEL_MIN_D", 0)
    monkeypatch.setenv("PIO_ALS_FUSED_GRAM", "on")  # interpret-mode hook
    k0, g0 = booked("als_fused")
    als.als_train(users, items, ratings, **kw)
    k1, g1 = booked("als_fused")
    assert k1 == k0 + 1
    # ONE FLOP formula across the op split: identical workload, identical
    # booked FLOPs
    assert g1 - g0 == pytest.approx(f1 - f0)
    # the XLA label did not absorb the kernel run
    assert booked("als_train")[0] == d1


def test_profile_route_validation():
    from incubator_predictionio_tpu.data.storage import Storage
    from incubator_predictionio_tpu.servers.admin import AdminServer

    Storage.configure({
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    })
    ad = AdminServer(ip="127.0.0.1", port=0)
    port = ad.start_background()

    def post(path):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}", data=b"", method="POST")
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                return resp.status
        except urllib.error.HTTPError as e:
            return e.code

    try:
        assert post("/profile?seconds=abc") == 400
        assert post("/profile?seconds=0") == 400
        assert post("/profile?seconds=9999") == 400
        # a capture in flight answers 409, never a second start_trace
        assert obs_profile._capture_lock.acquire(blocking=False)
        try:
            assert post("/profile?seconds=1") == 409
        finally:
            obs_profile._capture_lock.release()
    finally:
        ad.stop()
        Storage.reset()
