"""CoreWorkflow train/eval lifecycle (parity: workflow/CoreWorkflow.scala,
EvaluationWorkflowTest.scala)."""

import numpy as np
import pytest

from fake_engine import AP, QxMetric, make_engine, params
from incubator_predictionio_tpu.core import MetricEvaluator
from incubator_predictionio_tpu.core.evaluation import Evaluation
from incubator_predictionio_tpu.data.storage import Storage
from incubator_predictionio_tpu.workflow import CoreWorkflow, checkpoint


@pytest.fixture(autouse=True)
def mem_storage():
    Storage.configure({
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    })
    yield
    Storage.reset()


def test_run_train_lifecycle():
    engine = make_engine()
    instance_id = CoreWorkflow.run_train(
        engine, params(), engine_variant="v1", engine_factory="tests.fake"
    )
    instances = Storage.get_meta_data_engine_instances()
    inst = instances.get(instance_id)
    assert inst.status == "COMPLETED"
    assert inst.engine_variant == "v1"
    assert "algo0" in inst.algorithms_params
    # models restorable
    models = CoreWorkflow.load_models(instance_id)
    assert models[0].ap_id == 3
    # latest-completed resolution (what deploy uses)
    latest = instances.get_latest_completed("default", "NOT_VERSIONED", "v1")
    assert latest.id == instance_id


def test_run_train_nonzero_pod_process_trains_but_does_not_persist(
        monkeypatch):
    """In a `pio train --hosts` pod only process 0 owns storage writes —
    workers train their SPMD shard and return an empty instance id (the
    Spark executor-vs-driver split)."""
    import jax

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    engine = make_engine()
    assert CoreWorkflow.run_train(engine, params()) == ""
    assert Storage.get_meta_data_engine_instances().get_all() == []


def test_run_evaluation_nonzero_pod_process_computes_without_persisting(
        monkeypatch, tmp_path):
    import jax

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    engine = make_engine()
    evaluation = Evaluation()
    best = tmp_path / "best.json"
    evaluation.engine_evaluator = (
        engine, MetricEvaluator(QxMetric(), output_path=str(best)))
    iid, result = CoreWorkflow.run_evaluation(
        evaluation, [params(algos=[("algo0", AP(3))])])
    assert iid == ""
    assert result.best_score is not None      # the worker still computed
    assert not best.exists()                  # ...but process 0 owns best.json
    assert Storage.get_meta_data_evaluation_instances().get_all() == []
    # output_path restored for a later promotion to process 0
    assert evaluation.evaluator.output_path == str(best)


def test_host_materialize_recurses_into_dataclass_models():
    """Engine models are plain dataclasses, NOT registered pytrees — the
    collective host fetch must walk their fields by hand or pod-sharded
    arrays inside them would silently survive to the checkpoint encoder."""
    import dataclasses as dc

    import jax.numpy as jnp

    @dc.dataclass
    class Inner:
        arr: object

    @dc.dataclass(frozen=True)
    class Model:
        factors: object
        nested: Inner
        table: dict
        name: str

    m = Model(
        factors=jnp.arange(4.0),
        nested=Inner(arr=jnp.ones((2, 2))),
        table={"a": jnp.zeros(3), "b": "text"},
        name="m",
    )
    out = checkpoint.host_materialize([m])[0]
    assert isinstance(out.factors, np.ndarray)
    assert isinstance(out.nested.arr, np.ndarray)
    assert isinstance(out.table["a"], np.ndarray)
    assert out.table["b"] == "text" and out.name == "m"


def test_run_train_failure_marks_aborted():
    from fake_engine import FailingDataSource, Preparator0, Algorithm0, Serving0
    from incubator_predictionio_tpu.core import Engine

    engine = Engine(FailingDataSource, Preparator0, Algorithm0, Serving0)
    with pytest.raises(RuntimeError):
        CoreWorkflow.run_train(engine, params(algos=[("", AP(1))]))
    insts = Storage.get_meta_data_engine_instances().get_all()
    assert [i.status for i in insts] == ["ABORTED"]


def test_checkpoint_round_trip_with_jax_arrays():
    import jax.numpy as jnp

    model = {"w": jnp.arange(8, dtype=jnp.float32).reshape(2, 4),
             "meta": {"name": "m", "ids": [1, 2, 3]}}
    blob = checkpoint.dumps(model)
    back = checkpoint.loads(blob)
    assert isinstance(back["w"], np.ndarray)
    np.testing.assert_array_equal(back["w"], np.arange(8, dtype=np.float32).reshape(2, 4))
    assert back["meta"] == {"name": "m", "ids": [1, 2, 3]}
    restored = checkpoint.device_restore(back)
    import jax
    assert isinstance(restored["w"], jax.Array)


def test_checkpoint_v2_is_not_pickle_and_refuses_gadgets():
    """The v2 blob is msgpack behind a magic header: no pickle opcodes on
    the wire, and decode only ever constructs dataclasses."""
    blob = checkpoint.dumps({"x": 1})
    assert blob.startswith(b"PIOCKPT2")
    # a crafted blob naming a non-dataclass (os.system-style gadget) refuses
    import msgpack
    evil = b"PIOCKPT2" + msgpack.packb({
        "version": 2,
        "root": {"~pio~": "dc", "c": "os:system", "f": {}},
    }, use_bin_type=True)
    with pytest.raises(checkpoint.CheckpointError):
        checkpoint.loads(evil)


def test_checkpoint_legacy_pickle_loads_with_optout(monkeypatch):
    import io
    import pickle

    legacy = pickle.dumps((1, [{"w": 3}]))
    assert checkpoint.deserialize_models(legacy) == [{"w": 3}]
    monkeypatch.setenv("PIO_ALLOW_PICKLE_CHECKPOINTS", "0")
    with pytest.raises(checkpoint.CheckpointError):
        checkpoint.loads(legacy)


def test_checkpoint_rejects_arbitrary_objects():
    class NotAModel:
        pass

    with pytest.raises(checkpoint.CheckpointError):
        checkpoint.dumps(NotAModel())


def test_checkpoint_round_trips_template_models():
    """All five template model dataclasses survive the safe v2 format
    (VERDICT r2 #3 done-bar), including BiMaps, int-keyed dicts, tuples,
    and device arrays."""
    import jax.numpy as jnp

    from incubator_predictionio_tpu.data.bimap import BiMap
    from incubator_predictionio_tpu.models.recommendation.engine import (
        ALSModel,
    )

    model = ALSModel(
        user_factors=jnp.ones((3, 2), jnp.float32),
        item_factors=jnp.zeros((4, 2), jnp.float32),
        user_bimap=BiMap({"a": 0, "b": 1, "c": 2}),
        item_bimap=BiMap({"x": 0, "y": 1, "z": 2, "w": 3}),
        item_years={"x": 1999},
        item_categories={"y": ("drama", "war")},
        user_seen={0: np.array([1, 2], np.int32)},
    )
    back = checkpoint.deserialize_models(
        checkpoint.serialize_models([model], "i", None))[0]
    assert isinstance(back, ALSModel)
    assert back.user_bimap["b"] == 1 and back.user_bimap.inverse[2] == "c"
    assert back.item_categories["y"] == ("drama", "war")
    np.testing.assert_array_equal(back.user_seen[0], [1, 2])
    np.testing.assert_array_equal(np.asarray(back.user_factors),
                                  np.ones((3, 2), np.float32))


from incubator_predictionio_tpu.core.persistent_model import (
    LocalFileSystemPersistentModel,
)


class MyModel(LocalFileSystemPersistentModel):
    def __init__(self, value):
        self.value = value


def test_persistent_model_checkpoint(tmp_home):
    from incubator_predictionio_tpu.core.persistent_model import (
        PersistentModelManifest,
    )
    from incubator_predictionio_tpu.parallel.context import RuntimeContext

    ctx = RuntimeContext()
    blob = checkpoint.serialize_models([MyModel(42)], "inst-7", ctx)
    stored = checkpoint.deserialize_models(blob)
    assert isinstance(stored[0], PersistentModelManifest)
    loaded = stored[0].load(None, ctx)
    assert loaded.value == 42


def test_run_evaluation_lifecycle():
    engine = make_engine()
    evaluation = Evaluation()
    evaluation.engine_metric = (engine, QxMetric())
    candidates = [params(algos=[("algo0", AP(i))]) for i in (1, 4, 2)]
    instance_id, result = CoreWorkflow.run_evaluation(
        evaluation, candidates, evaluation_class="tests.Eval"
    )
    assert result.best_score.score == 4.0
    inst = Storage.get_meta_data_evaluation_instances().get(instance_id)
    assert inst.status == "EVALCOMPLETED"
    assert "4.0" in inst.evaluator_results
    assert inst.evaluator_results_json
    assert Storage.get_meta_data_evaluation_instances().get_completed()[0].id == instance_id


def test_checkpoint_round_trips_dates_and_datetimes():
    """Time-panel models (the stock template's trading-day index) carry
    datetime.date values; both date and datetime must round-trip without
    collapsing into each other (datetime is a date subclass)."""
    from datetime import date, datetime, timezone

    model = {
        "days": (date(2024, 3, 1), date(2024, 3, 4)),
        "stamp": datetime(2024, 3, 1, 9, 30, tzinfo=timezone.utc),
    }
    back = checkpoint.loads(checkpoint.dumps(model))
    assert back["days"] == (date(2024, 3, 1), date(2024, 3, 4))
    assert type(back["days"][0]) is date
    assert back["stamp"] == datetime(2024, 3, 1, 9, 30, tzinfo=timezone.utc)
    assert type(back["stamp"]) is datetime
