"""Two-stage MIPS serving: the tier-1 correctness contract.

The recall@20-vs-exhaustive gate (≥ 0.95 on the planted catalogue) is
THE promise that lets the auto-routers swap a linear scan for the
quantized coarse-scan + exact-rerank path (ops/mips.py). It is pinned
here at every mesh shape {1, 2, 4, 8} and with overlay fold-in keys
present, alongside the satellite contracts: int8 round-trip error,
candidate-stage determinism, the exact-tail merge (a fresh fold-in key
findable at recall 1.0), the O(delta) index update, the sharded-merge
numpy parity, and the zero-steady-state-recompile ladder.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from incubator_predictionio_tpu.ops import mips, topk
from incubator_predictionio_tpu.utils.planted import (
    exhaustive_top_k,
    planted_item_factors,
    planted_queries,
    recall_against_oracle,
)

N_ITEMS, RANK, K, N_QUERIES = 8192, 32, 20, 24


@pytest.fixture(scope="module")
def planted():
    vf = planted_item_factors(N_ITEMS, RANK, seed=3)
    queries = planted_queries(vf, N_QUERIES, seed=7)
    oracle = exhaustive_top_k(vf, queries, K)
    return vf, queries, oracle


@pytest.fixture
def mips_on(monkeypatch):
    monkeypatch.setenv("PIO_SERVE_MIPS", "on")


def _placed_table(vf, n):
    """vf placed over the first ``n`` virtual devices (n=1 → plain)."""
    if n == 1:
        return jax.device_put(vf)
    from incubator_predictionio_tpu.parallel.mesh import make_mesh
    from incubator_predictionio_tpu.parallel.placement import (
        make_placement,
    )

    if jax.device_count() < n:
        pytest.skip(f"needs {n} devices")
    mesh = make_mesh(devices=jax.devices()[:n])
    placement = make_placement(mesh, n_users=64, n_items=len(vf),
                               grow=True)
    return placement.place_table(vf, "item")


# ---------------------------------------------------------------------------
# THE recall gate — every mesh shape, with overlay fold-in keys present
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("n_shards", [1, 2, 4, 8])
def test_recall_gate_vs_exhaustive_oracle(planted, mips_on, n_shards):
    vf, queries, oracle = planted
    table = _placed_table(vf, n_shards)
    index = mips.build_index(table, N_ITEMS, seed=3)
    assert index.n_shards == n_shards

    # two-stage through the REAL auto-router (exhaustive is the oracle)
    got = np.stack([
        np.asarray(topk.score_and_top_k(
            jnp.asarray(q), table, k=K, valid_items=N_ITEMS))[1]
        .astype(np.int64)
        for q in queries
    ])
    recall, worst = recall_against_oracle(got, oracle, K)
    assert recall >= 0.95, (n_shards, recall, worst)

    # ...and the gate must still hold with overlay fold-in keys in the
    # exact tail (published vectors merge without disturbing base
    # results beyond their own ranks)
    fresh = np.stack([
        (queries[j] / np.linalg.norm(queries[j]) * 10.0)
        for j in range(4)
    ]).astype(np.float32)
    virtual = mips.publish_rows(table, fresh)
    assert virtual is not None and (virtual >= index.capacity).all()
    got2 = np.stack([
        np.asarray(topk.score_and_top_k(
            jnp.asarray(q), table, k=K, valid_items=N_ITEMS))[1]
        .astype(np.int64)
        for q in queries
    ])
    # each fresh key dominates its aligned query (exact merge, rank 0)
    for j in range(4):
        assert int(got2[j][0]) == int(virtual[j])
    # the rest of each top-k is still the oracle's
    recall2, _ = recall_against_oracle(got2, oracle, K)
    assert recall2 >= 0.90, recall2  # ≤ 1 slot lost to the fresh key


def test_auto_routing_and_fallbacks(planted, monkeypatch):
    vf, queries, oracle = planted
    table = jax.device_put(vf)
    mips.build_index(table, N_ITEMS, seed=3)

    monkeypatch.setenv("PIO_SERVE_MIPS", "on")
    assert mips.route(table, k=K) is not None
    # filtered queries always fall back (the mask can defeat any
    # candidate budget; exhaustive honors it exactly)
    assert mips.route(table, k=K,
                      allowed_mask=np.ones(N_ITEMS, bool)) is None
    # top-everything has no approximate version
    assert mips.route(table, k=N_ITEMS) is None

    monkeypatch.setenv("PIO_SERVE_MIPS", "off")
    assert mips.route(table, k=K) is None
    packed = np.asarray(topk.score_and_top_k(
        jnp.asarray(queries[0]), table, k=K))
    assert set(packed[1].astype(np.int64)) == set(oracle[0])

    # auto mode: the registered index routes, an unregistered table
    # never does
    monkeypatch.setenv("PIO_SERVE_MIPS", "auto")
    assert mips.route(table, k=K) is not None
    other = jax.device_put(vf[: 128])
    assert mips.route(other, k=K) is None
    # an exclusion list rivaling the candidate budget falls back too —
    # a power user's seen set is exactly what dominates the coarse cut,
    # and a mostly-masked fixed-width rerank would under-fill top-k
    small_ex = jnp.asarray(np.arange(64, dtype=np.int32))
    big_ex = jnp.asarray(np.arange(1024, dtype=np.int32))
    assert mips.route(table, k=K, exclude=small_ex) is not None
    assert mips.route(table, k=K, exclude=big_ex) is None
    # ...and the auto BUILD gate keeps tiny catalogues exhaustive
    assert not mips.build_enabled(N_ITEMS)      # < 65536 floor
    monkeypatch.setenv("PIO_SERVE_MIPS_MIN_ITEMS", "4096")
    assert mips.build_enabled(N_ITEMS)


# ---------------------------------------------------------------------------
# satellite contracts
# ---------------------------------------------------------------------------

def test_int8_roundtrip_cosine_error(planted):
    """Symmetric per-row int8: the quantization the coarse stage ranks
    with. Round-trip cosine error stays ≤ 1e-4 — far inside what a
    1024-wide exact rerank absorbs."""
    vf, _q, _o = planted
    codes, scales = mips._quantize_int8(vf)
    rt = codes.astype(np.float32) * scales[:, None]
    cos = (np.einsum("ik,ik->i", rt, vf)
           / np.maximum(np.linalg.norm(rt, axis=1)
                        * np.linalg.norm(vf, axis=1), 1e-12))
    assert float(cos.min()) >= 1.0 - 1e-4, float(cos.min())
    # and the bf16 view is a faithful cast
    bf = vf.astype(jnp.bfloat16).astype(np.float32)
    rel = np.abs(bf - vf) / np.maximum(np.abs(vf), 1e-6)
    assert float(np.median(rel)) < 1e-2


def test_bf16_view_build_and_update(planted, mips_on, monkeypatch):
    """PIO_SERVE_MIPS_QUANT=bf16 at BUILD time: only the bf16 view is
    materialized (the int8 side is a placeholder), the gate still
    holds, and the O(delta) splice updates the view that exists."""
    monkeypatch.setenv("PIO_SERVE_MIPS_QUANT", "bf16")
    vf, queries, oracle = planted
    table = jax.device_put(vf)
    index = mips.build_index(table, N_ITEMS, seed=3)
    assert index.quant == "bf16"
    assert index.capacity == N_ITEMS
    assert index.codes.shape[0] < N_ITEMS  # placeholder, not a copy
    got = np.stack([
        mips.mips_score_and_top_k(q, table, index, K)[1]
        .astype(np.int64) for q in queries])
    recall, _ = recall_against_oracle(got, oracle, K)
    assert recall >= 0.95, recall
    vf2 = vf.copy()
    vf2[10] *= 2.0
    table2 = jax.device_put(vf2)
    assert mips.update_index(table, table2, N_ITEMS,
                             np.asarray([10])) is index
    qv = (vf2[10] / np.linalg.norm(vf2[10])).astype(np.float32)
    got2 = mips.mips_score_and_top_k(qv, table2, index, 10)
    assert 10 in got2[1].astype(np.int64).tolist()


def test_candidate_stage_determinism(planted, mips_on):
    """Same seed → bit-identical index; same query → identical
    candidates and results, call after call."""
    vf, queries, _oracle = planted
    t1 = jax.device_put(vf)
    t2 = jax.device_put(vf.copy())
    a = mips.build_index(t1, N_ITEMS, seed=3, register=False)
    b = mips.build_index(t2, N_ITEMS, seed=3, register=False)
    assert np.array_equal(np.asarray(a.centroids),
                          np.asarray(b.centroids))
    assert np.array_equal(a.assign, b.assign)
    assert np.array_equal(np.asarray(a.members), np.asarray(b.members))
    assert a.cap == b.cap and a.c_total == b.c_total

    q = queries[0]
    r1 = mips.mips_score_and_top_k(q, t1, a, K)
    r2 = mips.mips_score_and_top_k(q, t1, a, K)
    assert np.array_equal(r1, r2)


def test_overlay_key_exact_merge(planted, mips_on):
    """A fresh fold-in key must be findable at recall 1.0 the moment it
    publishes, scored EXACTLY; known-row publishes override the stale
    base row; excluded ids never surface from the tail."""
    vf, queries, _oracle = planted
    table = jax.device_put(vf)
    index = mips.build_index(table, N_ITEMS, seed=3)

    q = queries[2]
    fresh = (q / np.linalg.norm(q) * 10.0).astype(np.float32)
    (vid,) = mips.publish_rows(table, fresh[None, :])
    packed = np.asarray(topk.score_and_top_k(jnp.asarray(q), table,
                                             k=K))
    assert int(packed[1][0]) == int(vid)          # recall 1.0
    assert np.isclose(packed[0][0], float(fresh @ q), rtol=1e-5)

    # known-row publish: the published solve (not the base factor row)
    # is what serves — exact override via the tail
    row = 123
    newvec = (queries[3] / np.linalg.norm(queries[3])
              * 9.0).astype(np.float32)
    mips.publish_rows(table, newvec[None, :], rows=[row])
    p2 = np.asarray(topk.score_and_top_k(jnp.asarray(queries[3]),
                                         table, k=K))
    ids = p2[1].astype(np.int64).tolist()
    assert row in ids
    assert np.isclose(p2[0][ids.index(row)],
                      float(newvec @ queries[3]), rtol=1e-5)

    # exclusions reach the tail too
    excl = jnp.asarray(np.asarray([vid], np.int32))
    p3 = np.asarray(topk.score_and_top_k(jnp.asarray(q), table, k=K,
                                         exclude=excl))
    assert int(vid) not in p3[1].astype(np.int64).tolist()


def test_update_index_is_o_delta(planted, mips_on):
    """Continuation-retrain seam: touched rows re-quantize and re-home,
    untouched rows keep their codes, the index re-registers under the
    new table, and a capacity overflow honestly refuses (→ rebuild)."""
    vf, queries, _oracle = planted
    table = jax.device_put(vf)
    index = mips.build_index(table, N_ITEMS, seed=3)
    codes_before = np.asarray(index.codes).copy()
    built_before = index.built_at

    vf2 = vf.copy()
    touched = np.asarray([5, 77, 4095, 8000])
    vf2[touched] = planted_item_factors(4, RANK, seed=99) * 3.0
    table2 = jax.device_put(vf2)
    assert mips.update_index(table, table2, N_ITEMS, touched) is index
    assert mips.index_for(table2) is index
    assert mips.index_for(table) is None
    assert index.delta_updates == 1
    assert index.built_at >= built_before

    codes_after = np.asarray(index.codes)
    untouched = np.setdiff1d(np.arange(N_ITEMS), touched)
    assert np.array_equal(codes_after[untouched],
                          codes_before[untouched])
    assert not np.array_equal(codes_after[touched],
                              codes_before[touched])

    # every moved row is findable through the updated buckets
    for row in touched:
        qv = (vf2[row] / np.linalg.norm(vf2[row])).astype(np.float32)
        got = mips.mips_score_and_top_k(qv, table2, index, 10)
        assert int(row) in got[1].astype(np.int64).tolist(), row
    # recall against the NEW oracle stays at the gate
    oracle2 = exhaustive_top_k(vf2, queries, K)
    got2 = np.stack([
        mips.mips_score_and_top_k(q, table2, index, K)[1]
        .astype(np.int64) for q in queries])
    recall, _ = recall_against_oracle(got2, oracle2, K)
    assert recall >= 0.95, recall

    # geometry change → honest refusal, the caller rebuilds
    bigger = jax.device_put(np.concatenate([vf2, vf2[:8]]))
    assert mips.update_index(table2, bigger, N_ITEMS + 8,
                             np.asarray([])) is None


@pytest.mark.parametrize("n_shards", [1, 2, 4, 8])
def test_sharded_merge_matches_numpy_reference(planted, mips_on,
                                               n_shards):
    """Mesh parity of the sharded candidate merge: the device result
    equals a host numpy re-implementation of the SAME per-shard quota
    algorithm, shape for shape."""
    vf, queries, _oracle = planted
    table = _placed_table(vf, n_shards)
    index = mips.build_index(table, N_ITEMS, seed=3)
    nprobe_l, n_cand_l, _c, _r = mips._quotas(index, K)
    cent = index.centroids_np
    cmax = np.asarray(index.cmax)
    ccos = np.asarray(index.crad_cos)
    csin = np.asarray(index.crad_sin)
    members = np.asarray(index.members)
    codes = np.asarray(index.codes).astype(np.float32)
    scales = np.asarray(index.scales)

    for q in queries[:6]:
        per_shard = []
        for s in range(index.n_shards):
            lo = s * index.c_local
            sl = slice(lo, lo + index.c_local)
            cs = cent[sl] @ q
            ortho = np.sqrt(np.maximum(float(q @ q) - cs * cs, 0.0))
            bound = cmax[sl] * (cs * ccos[sl] + ortho * csin[sl])
            probe = np.argsort(-bound, kind="stable")[:nprobe_l] + lo
            cand = members[probe].ravel()
            cand = cand[cand >= 0]
            coarse = (codes[cand] @ q) * scales[cand]
            keep = cand[np.argsort(-coarse, kind="stable")[:n_cand_l]]
            exact = vf[keep] @ q
            kk = min(K, n_cand_l)
            top = keep[np.argsort(-exact, kind="stable")[:kk]]
            per_shard.append((vf[top] @ q, top))
        all_s = np.concatenate([s for s, _i in per_shard])
        all_i = np.concatenate([i for _s, i in per_shard])
        order = np.argsort(-all_s, kind="stable")[:K]
        want_ids = set(all_i[order].astype(np.int64))
        got = np.asarray(topk.score_and_top_k(
            jnp.asarray(q), table, k=K, valid_items=N_ITEMS))
        got_ids = set(got[1].astype(np.int64))
        assert got_ids == want_ids, (n_shards, got_ids ^ want_ids)
        assert np.allclose(np.sort(got[0])[::-1],
                           np.sort(all_s[order])[::-1], rtol=1e-5)


def test_zero_steady_state_recompiles(planted, mips_on):
    """The pow2 ladder contract, MIPS edition: once the shapes are
    warm, repeated singleton/batched queries compile NOTHING new."""
    vf, queries, _oracle = planted
    table = jax.device_put(vf)
    mips.build_index(table, N_ITEMS, seed=3)
    uf = jax.device_put(queries)
    # warm: singleton, user-row, and the batch rungs {2..16}
    np.asarray(topk.score_and_top_k(jnp.asarray(queries[0]), table,
                                    k=K))
    np.asarray(topk.score_user_and_top_k(uf, table, 0, k=K))
    for rung in (2, 4, 8, 16):
        np.asarray(topk.batch_score_top_k(uf, table,
                                          np.arange(rung), k=K))
    warm = topk.serve_compile_cache_size()
    for _ in range(3):
        np.asarray(topk.score_and_top_k(jnp.asarray(queries[1]), table,
                                        k=K))
        np.asarray(topk.score_user_and_top_k(uf, table, 2, k=K))
        for rung in (2, 4, 8, 16):
            np.asarray(topk.batch_score_top_k(
                uf, table, np.arange(rung) % N_QUERIES, k=K))
    assert topk.serve_compile_cache_size() == warm


def test_scan_accounting_and_probe_gauge(planted, mips_on):
    """pio_serve_candidates_scanned_total{stage} books the two-stage
    budgets (and the exhaustive fallback books the full table);
    recall_probe publishes pio_serve_mips_recall; the index-age
    collector exposes pio_mips_index_age_seconds."""
    from incubator_predictionio_tpu.obs import metrics as obs_metrics

    vf, queries, _oracle = planted
    table = jax.device_put(vf)
    index = mips.build_index(table, N_ITEMS, seed=3)
    fam = obs_metrics.REGISTRY.get("pio_serve_candidates_scanned_total")
    _np_l, coarse, rerank = mips.scan_budget(index, K)
    c0 = fam.labels(stage="coarse").value
    r0 = fam.labels(stage="rerank").value
    np.asarray(topk.score_and_top_k(jnp.asarray(queries[0]), table,
                                    k=K))
    assert fam.labels(stage="coarse").value - c0 == coarse
    assert fam.labels(stage="rerank").value - r0 == rerank
    e0 = fam.labels(stage="exhaustive").value
    os.environ["PIO_SERVE_MIPS"] = "off"
    try:
        np.asarray(topk.score_and_top_k(jnp.asarray(queries[0]), table,
                                        k=K))
    finally:
        os.environ["PIO_SERVE_MIPS"] = "on"
    assert fam.labels(stage="exhaustive").value - e0 == N_ITEMS

    recall = mips.recall_probe(table, index, host_factors=vf)
    assert recall is not None and recall >= 0.9
    gauge = obs_metrics.REGISTRY.get("pio_serve_mips_recall")
    assert gauge.value == pytest.approx(recall)
    exposition = obs_metrics.REGISTRY.expose()
    assert "pio_mips_index_age_seconds" in exposition


def test_engine_builds_index_and_serves_through_it(planted,
                                                   monkeypatch):
    """The train→serve seam end to end: ALSAlgorithm registers an index
    for its item table when the knob allows, and predict() routes
    through the two-stage path (device serving forced)."""
    from incubator_predictionio_tpu.data.bimap import BiMap
    from incubator_predictionio_tpu.models.recommendation.engine import (
        ALSAlgorithm,
        ALSAlgorithmParams,
        PreparedData,
        Query,
    )
    from incubator_predictionio_tpu.obs import metrics as obs_metrics
    from incubator_predictionio_tpu.parallel.context import (
        RuntimeContext,
    )

    monkeypatch.setenv("PIO_SERVE_MIPS", "on")
    monkeypatch.setenv("PIO_HOST_SERVE_MAX_ELEMS", "0")
    rng = np.random.default_rng(5)
    n_users, n_items, nnz = 64, 512, 4096
    pd = PreparedData(
        users=rng.integers(0, n_users, nnz).astype(np.int32),
        items=rng.integers(0, n_items, nnz).astype(np.int32),
        ratings=rng.uniform(1, 5, nnz).astype(np.float32),
        user_bimap=BiMap({f"u{i}": i for i in range(n_users)}),
        item_bimap=BiMap({f"i{i}": i for i in range(n_items)}),
        item_years={}, item_categories={},
    )
    algo = ALSAlgorithm(ALSAlgorithmParams(rank=8, num_iterations=2,
                                           seed=1))
    model = algo.train(RuntimeContext(), pd)
    index = mips.index_for(model.item_factors)
    assert index is not None and index.n_items == n_items

    fam = obs_metrics.REGISTRY.get("pio_serve_candidates_scanned_total")
    before = fam.labels(stage="rerank").value
    result = algo.predict(model, Query(user="u3", num=5))
    assert len(result.item_scores) == 5
    assert fam.labels(stage="rerank").value > before  # two-stage served


def test_similarproduct_index_overlay_and_virtual_items(monkeypatch):
    """The item-side seam end to end: the similarproduct engine builds
    an index over its normalized serving table, plain queries route
    two-stage, and an overlay-published BRAND-NEW item (never in the
    model) is servable as a result through the exact tail + the
    virtual-id map."""
    from incubator_predictionio_tpu.data.bimap import BiMap
    from incubator_predictionio_tpu.models.similarproduct.engine import (
        ALSAlgorithmParams,
        PreparedData,
        Query,
        SimilarProductAlgorithm,
    )
    from incubator_predictionio_tpu.obs import metrics as obs_metrics
    from incubator_predictionio_tpu.parallel.context import (
        RuntimeContext,
    )

    monkeypatch.setenv("PIO_SERVE_MIPS", "on")
    monkeypatch.setenv("PIO_HOST_SERVE_MAX_ELEMS", "0")
    rng = np.random.default_rng(9)
    n_users, n_items, nnz = 48, 400, 3000
    pd = PreparedData(
        users=rng.integers(0, n_users, nnz).astype(np.int32),
        items=rng.integers(0, n_items, nnz).astype(np.int32),
        weights=rng.uniform(0.5, 3.0, nnz).astype(np.float32),
        user_bimap=BiMap({f"u{i}": i for i in range(n_users)}),
        item_bimap=BiMap({f"i{i}": i for i in range(n_items)}),
        item_categories={},
    )
    algo = SimilarProductAlgorithm(
        ALSAlgorithmParams(rank=8, num_iterations=2, seed=2))
    model = algo.train(RuntimeContext(), pd)
    index = mips.index_for(model.item_factors_norm)
    assert index is not None and index.n_items == n_items

    fam = obs_metrics.REGISTRY.get("pio_serve_candidates_scanned_total")
    before = fam.labels(stage="rerank").value
    result = algo.predict(model, Query(items=("i7",), num=5))
    assert len(result.item_scores) == 5
    assert "i7" not in [s.item for s in result.item_scores]
    assert fam.labels(stage="rerank").value > before  # routed two-stage

    # brand-new item published through the overlay's index_sink: it
    # must be findable as a RESULT at its exact cosine score
    overlay_sink_holder = {}

    class _FakeOverlay:  # capture the sink without storage machinery
        def __init__(self, *a, **kw):
            overlay_sink_holder["sink"] = kw["index_sink"]
            self.enabled = False

    import incubator_predictionio_tpu.speed.overlay as ov_mod

    monkeypatch.setattr(ov_mod, "SpeedOverlay", _FakeOverlay)
    algo.make_speed_overlay(model, app_name="App", channel_name=None)
    base = np.asarray(model.item_factors_norm)
    fresh = (0.7 * base[7] + 0.3 * base[11]).astype(np.float32)
    fresh /= np.linalg.norm(fresh)
    overlay_sink_holder["sink"](["brand-new-item"], [fresh])
    assert index.tail_size() == 1
    got = algo.predict(model, Query(items=("i7",), num=5))
    names = [s.item for s in got.item_scores]
    assert "brand-new-item" in names, names
    hit = got.item_scores[names.index("brand-new-item")]
    qv = base[7] / np.linalg.norm(base[7])
    assert hit.score == pytest.approx(float(fresh @ qv), rel=1e-5)
    # ...and querying BY the new item must not return the item itself
    # (its virtual tail id is excluded like any base query-item row)
    overlay = type("Ov", (), {"lookup": lambda self, key:
                              fresh if key == "brand-new-item" else None,
                              "enabled": True})()
    algo.attach_speed_overlay(overlay)
    try:
        self_q = algo.predict(model, Query(items=("brand-new-item",),
                                           num=5))
        assert "brand-new-item" not in [s.item
                                        for s in self_q.item_scores]
    finally:
        algo.attach_speed_overlay(None)
