"""Friend-recommendation template (KDD-2012 scenario) — keyword
similarity, random baseline, and dense device SimRank (parity:
examples/experimental/scala-{local,parallel}-friend-recommendation)."""

import numpy as np
import pytest

from incubator_predictionio_tpu.core import EngineParams
from incubator_predictionio_tpu.data.datamap import DataMap
from incubator_predictionio_tpu.data.event import Event
from incubator_predictionio_tpu.data.storage import App, Storage
from incubator_predictionio_tpu.models.friendrecommendation import (
    DataSourceParams,
    FriendRecommendationEngine,
    KeywordSimilarityAlgoParams,
    Query,
    SimRankAlgoParams,
)
from incubator_predictionio_tpu.parallel.context import RuntimeContext


@pytest.fixture(autouse=True)
def mem_storage():
    Storage.configure({
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    })
    yield
    Storage.reset()


@pytest.fixture
def seeded_app():
    Storage.get_meta_data_apps().insert(App(0, "frapp"))
    app_id = Storage.get_meta_data_apps().get_by_name("frapp").id
    dao = Storage.get_events()
    kw = {
        ("user", "u1"): {"1": 0.6, "2": 0.4},
        ("user", "u2"): {"3": 1.0},
        ("item", "g1"): {"1": 0.5, "2": 0.5},   # overlaps u1
        ("item", "g2"): {"9": 1.0},             # overlaps nobody
    }
    for (etype, eid), words in kw.items():
        dao.insert(Event(
            event="$set", entity_type=etype, entity_id=eid,
            properties=DataMap({"keywords": words})), app_id)
    # graph: u3 follows both u1 and u2 (shared in-neighbor → SimRank
    # similarity between u1 and u2); both act on g1
    for (et, a), (tt, b), name in (
        (("user", "u3"), ("user", "u1"), "follow"),
        (("user", "u3"), ("user", "u2"), "follow"),
        (("user", "u1"), ("item", "g1"), "action"),
        (("user", "u2"), ("item", "g1"), "action"),
    ):
        dao.insert(Event(
            event=name, entity_type=et, entity_id=a,
            target_entity_type=tt, target_entity_id=b), app_id)
    return app_id


def _ep(algo, params):
    return EngineParams(
        data_source_params=("", DataSourceParams(app_name="frapp")),
        algorithm_params_list=[(algo, params)],
    )


def test_keyword_similarity_confidence_and_acceptance(seeded_app):
    engine = FriendRecommendationEngine().apply()
    ep = _ep("keyword", KeywordSimilarityAlgoParams(sim_weight=2.0,
                                                    sim_threshold=0.5))
    models = engine.train(RuntimeContext(), ep)
    algo = engine.algorithms(ep)[0]
    p = algo.predict(models[0], Query(user="u1", item="g1"))
    # Σ w_u·w_i = 0.6*0.5 + 0.4*0.5 = 0.5; 0.5*2.0 >= 0.5 → accepted
    assert p.confidence == pytest.approx(0.5)
    assert p.acceptance
    # no keyword overlap → 0 confidence, rejected
    p2 = algo.predict(models[0], Query(user="u2", item="g1"))
    assert p2.confidence == pytest.approx(0.0) and not p2.acceptance
    # unseen user behaves like the reference's empty-map case
    p3 = algo.predict(models[0], Query(user="ghost", item="g1"))
    assert p3.confidence == 0.0


def test_random_baseline_is_deterministic(seeded_app):
    engine = FriendRecommendationEngine().apply()
    from incubator_predictionio_tpu.models.friendrecommendation.engine import (
        RandomAlgoParams,
    )

    ep = _ep("random", RandomAlgoParams(seed=5))
    models = engine.train(RuntimeContext(), ep)
    algo = engine.algorithms(ep)[0]
    a = algo.predict(models[0], Query(user="u1", item="g1"))
    b = algo.predict(models[0], Query(user="u1", item="g1"))
    assert a == b
    assert 0.0 <= a.confidence < 1.0


def test_simrank_scores_structural_similarity(seeded_app):
    engine = FriendRecommendationEngine().apply()
    ep = _ep("simrank", SimRankAlgoParams(iterations=8,
                                          acceptance_threshold=0.05))
    models = engine.train(RuntimeContext(), ep)
    algo = engine.algorithms(ep)[0]
    # u1 and u2 share the in-linked... u1→g1 and u2→g1: the QUERY pair is
    # (user, item); u1 vs g1 share no in-neighbors → low, while u1/u2
    # both point at g1 so sim(u1, u2) > 0 — query the user pair via the
    # item slot fallback
    p_users = algo.predict(models[0], Query(user="u1", item="u2"))
    assert p_users.confidence > 0.0
    p_cross = algo.predict(models[0], Query(user="u2", item="g1"))
    assert p_cross.confidence >= 0.0
    assert algo.predict(
        models[0], Query(user="ghost", item="g1")).confidence == 0.0


def test_simrank_matches_naive_reference():
    """Dense device SimRank equals a naive per-pair python SimRank."""
    from incubator_predictionio_tpu.ops.simrank import simrank

    edges = [(0, 2), (1, 2), (0, 3), (1, 3), (3, 2), (2, 4), (3, 4)]
    src = np.array([a for a, _ in edges])
    dst = np.array([b for _, b in edges])
    n, c, iters = 5, 0.8, 12
    got = simrank(src, dst, n, decay=c, iterations=iters)

    in_nb = {v: [a for a, b in edges if b == v] for v in range(n)}
    s = np.eye(n)
    for _ in range(iters):
        nxt = np.eye(n)
        for a in range(n):
            for b in range(n):
                if a == b:
                    continue
                na, nb = in_nb[a], in_nb[b]
                if not na or not nb:
                    nxt[a, b] = 0.0
                    continue
                nxt[a, b] = c * sum(
                    s[x, y] for x in na for y in nb) / (len(na) * len(nb))
        s = nxt
    np.testing.assert_allclose(got, s, atol=1e-4)
