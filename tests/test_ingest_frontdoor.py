"""Ingest front door (serving/frontdoor.py IngestFrontDoor).

The front of the planet-scale ingest path: event POSTs spray across a
pool of EventServer writers with the circuit-breaker/retry discipline of
the query front door, `/batches/events.json` aliases the batch route,
query strings survive forwarding, and a rolling writer reload drains
in-flight requests so a concurrent write stream loses ZERO events —
the ISSUE-17 soak acceptance, in miniature."""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from incubator_predictionio_tpu.data.storage import (
    AccessKey,
    App,
    Storage,
)
from incubator_predictionio_tpu.serving.frontdoor import (
    FrontDoorConfig,
    IngestFrontDoor,
)
from incubator_predictionio_tpu.servers.event_server import (
    EventServer,
    EventServerConfig,
)

pytestmark = pytest.mark.skipif(
    __import__("incubator_predictionio_tpu.native", fromlist=["load"]).load()
    is None,
    reason="native library unavailable",
)


@pytest.fixture
def door(tmp_path, monkeypatch):
    """2 EventServer writers over a 2-writer-shard cpplog store, behind
    an IngestFrontDoor."""
    monkeypatch.setenv("PIO_LOG_SHARDS", "2")
    Storage.configure({
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_SOURCES_LOG_TYPE": "cpplog",
        "PIO_STORAGE_SOURCES_LOG_PATH": str(tmp_path),
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "LOG",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    })
    app_id = Storage.get_meta_data_apps().insert(App(id=0, name="DoorApp"))
    Storage.get_meta_data_access_keys().insert(
        AccessKey(key="k123", appid=app_id, events=[]))
    Storage.get_events().init(app_id)
    writers = [EventServer(EventServerConfig(ip="127.0.0.1", port=0))
               for _ in range(2)]
    ports = [w.start_background() for w in writers]
    fd = IngestFrontDoor([("127.0.0.1", p) for p in ports],
                         FrontDoorConfig(server_key="k123"))
    dport = fd.start_background()
    yield fd, f"http://127.0.0.1:{dport}", app_id
    fd.stop()
    for w in writers:
        w.stop()
    Storage.reset()


def _post(base, path, body):
    req = urllib.request.Request(
        f"{base}{path}", json.dumps(body).encode(),
        {"Content-Type": "application/json"})
    with urllib.request.urlopen(req) as resp:
        return resp.status, json.loads(resp.read() or b"null")


def _mk(i):
    return {"event": "rate", "entityType": "user", "entityId": f"u{i}",
            "targetEntityType": "item", "targetEntityId": f"i{i % 7}",
            "properties": {"rating": float(i % 5) + 0.5}}


def _count(app_id):
    return len(Storage.get_events().scan_interactions(
        app_id=app_id, entity_type="user", target_entity_type="item",
        event_names=("rate",), value_prop="rating"))


def test_event_routes_and_batches_alias(door):
    _fd, base, app_id = door
    # single event; the accessKey query string must survive forwarding
    st, body = _post(base, "/events.json?accessKey=k123", _mk(0))
    assert st == 201 and "eventId" in body
    # batch through BOTH spellings of the batch route
    st, res = _post(base, "/batch/events.json?accessKey=k123",
                    [_mk(i) for i in range(1, 21)])
    assert st == 200 and all(r["status"] == 201 for r in res)
    st, res = _post(base, "/batches/events.json?accessKey=k123",
                    [_mk(i) for i in range(21, 41)])
    assert st == 200 and all(r["status"] == 201 for r in res)
    assert _count(app_id) == 41


def test_bad_access_key_rejected_through_door(door):
    _fd, base, _app_id = door
    with pytest.raises(urllib.error.HTTPError) as exc:
        _post(base, "/events.json?accessKey=WRONG", _mk(0))
    assert exc.value.code == 401


def test_rolling_reload_drops_zero_events(door):
    """Concurrent pumps keep writing while every writer is reloaded in
    sequence; every accepted POST must be in the log afterwards."""
    fd, base, app_id = door
    sent, errors = [], []

    def pump(tid):
        for j in range(8):
            batch = [_mk(1000 + tid * 100 + j * 10 + x) for x in range(10)]
            try:
                st, res = _post(
                    base, "/batch/events.json?accessKey=k123", batch)
                assert st == 200, st
                ok = sum(1 for r in res if r["status"] == 201)
                assert ok == len(batch), res
                sent.append(ok)
            except Exception as e:  # surfaced below; a drop fails the test
                errors.append(repr(e))

    threads = [threading.Thread(target=pump, args=(t,)) for t in range(3)]
    for t in threads:
        t.start()
    time.sleep(0.05)
    out = fd.rolling_reload(timeout=60)
    for t in threads:
        t.join()
    assert not errors, errors[:3]
    assert out["reloaded"] == 2 and out["dropped"] == 0, out
    assert _count(app_id) == sum(sent)
    counts = fd.stats()["counts"]
    assert sum(counts.values()) > 0
