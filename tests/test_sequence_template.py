"""Sequence (next-item transformer) engine: ops + full DASE flow."""

import numpy as np
import pytest

from incubator_predictionio_tpu.ops.transformer import (
    sasrec_fit,
    sasrec_topk,
    transformer_init,
)


def _pattern_sequences(n_items=12, n_seqs=64, length=8, seed=0):
    """Cyclic sessions: item i is always followed by i+1 (mod n_items)."""
    rng = np.random.default_rng(seed)
    rows = np.zeros((n_seqs, length), np.int32)
    for r in range(n_seqs):
        start = rng.integers(1, n_items + 1)
        rows[r] = [(start - 1 + j) % n_items + 1 for j in range(length)]
    return rows


def test_sasrec_learns_cyclic_pattern():
    import jax.numpy as jnp

    n_items = 12
    seqs = _pattern_sequences(n_items)
    w, losses = sasrec_fit(seqs, n_items=n_items, d_model=32, n_heads=2,
                           n_layers=1, epochs=60, batch_size=32,
                           learning_rate=3e-3, seed=0)
    assert losses[-1] < losses[0] * 0.5, losses
    # history ...→ 3 → 4 → 5: next must be 6
    tokens = np.zeros((1, 8), np.int32)
    tokens[0, -3:] = [3, 4, 5]
    scores, ids = sasrec_topk(w, jnp.asarray(tokens), n_heads=2, k=3)
    assert 6 in np.asarray(ids[0]), np.asarray(ids)


def test_sasrec_topk_excludes_history_and_pad():
    import jax.numpy as jnp

    w = transformer_init(__import__("jax").random.key(0), n_items=20,
                         max_len=8, d_model=16, n_layers=1)
    tokens = np.zeros((1, 8), np.int32)
    tokens[0, -4:] = [5, 6, 7, 8]
    scores, ids = sasrec_topk(w, jnp.asarray(tokens), n_heads=2, k=10)
    ids = set(np.asarray(ids[0]).tolist())
    assert 0 not in ids
    assert not ids & {5, 6, 7, 8}


def test_sasrec_fit_with_ring_attention_mesh():
    """Sequence-parallel training: ring attention over the sp axis gives the
    same learning signal (loss decreases; smoke parity on tiny shapes)."""
    import functools

    import jax
    from jax.sharding import Mesh

    from incubator_predictionio_tpu.parallel.mesh import SEQ_AXIS
    from incubator_predictionio_tpu.parallel.ring import ring_attention

    # seq len after the fit's [:, :-1] shift is 7 → pad to len 8 so the sp
    # axis (4) divides it
    seqs = _pattern_sequences(length=9)
    mesh = Mesh(np.array(jax.devices()[:4]), (SEQ_AXIS,))
    attn = functools.partial(ring_attention, mesh=mesh)
    w, losses = sasrec_fit(seqs, n_items=12, d_model=16, n_heads=2,
                           n_layers=1, epochs=10, batch_size=32,
                           learning_rate=3e-3, seed=0, attn_fn=attn)
    assert losses[-1] < losses[0]


@pytest.fixture
def seeded_sequence_app(tmp_home):
    from datetime import datetime, timedelta, timezone

    from incubator_predictionio_tpu.cli import commands
    from incubator_predictionio_tpu.data.event import Event
    from incubator_predictionio_tpu.data.store import EventStore
    from incubator_predictionio_tpu.data.storage import Storage

    Storage.reset()
    commands.app_new("seqapp", access_key="sk")
    t0 = datetime(2026, 1, 1, tzinfo=timezone.utc)
    events = []
    n_items = 10
    for u in range(32):
        start = u % n_items
        for j in range(6):
            item = (start + j) % n_items
            events.append(Event(
                event="view", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item", target_entity_id=f"i{item}",
                event_time=t0 + timedelta(minutes=u * 10 + j),
            ))
    EventStore.write(events, app_name="seqapp")
    yield "seqapp"
    Storage.reset()


def test_sequence_engine_end_to_end(seeded_sequence_app):
    from incubator_predictionio_tpu.core import EngineParams
    from incubator_predictionio_tpu.models.sequence import (
        Query, SeqRecAlgorithmParams, SequenceEngine,
    )
    from incubator_predictionio_tpu.models.sequence.engine import (
        DataSourceParams, PreparatorParams,
    )
    from incubator_predictionio_tpu.parallel.context import RuntimeContext

    engine = SequenceEngine().apply()
    params = EngineParams(
        data_source_params=("", DataSourceParams(app_name=seeded_sequence_app)),
        preparator_params=("", PreparatorParams(max_len=8)),
        algorithm_params_list=[
            ("sasrec", SeqRecAlgorithmParams(
                app_name=seeded_sequence_app, d_model=16, n_heads=2,
                n_layers=1, epochs=30, batch_size=16, learning_rate=3e-3,
                seed=0,
            )),
        ],
    )
    ctx = RuntimeContext(seed=0)
    models = engine.train(ctx, params)
    assert len(models) == 1

    _, _, algos, serving = engine.components(params)
    algos[0].prepare_model(ctx, models[0])

    # u0 viewed i0..i5 in order; next should be i6 (cyclic pattern across
    # users makes i(start+6 mod 10) the learned continuation)
    res = serving.serve(
        Query(user="u0", num=3),
        [algos[0].predict(models[0], Query(user="u0", num=3))],
    )
    assert len(res.item_scores) == 3
    assert all(s.item.startswith("i") for s in res.item_scores)
    seen = {f"i{j}" for j in range(6)}
    assert {s.item for s in res.item_scores} & seen == set()

    # stateless client passing history explicitly
    res2 = algos[0].predict(
        models[0], Query(user="nobody", num=2, recent_items=("i2", "i3")),
    )
    assert len(res2.item_scores) == 2

    # unknown user with no history → empty result, not an error
    res3 = algos[0].predict(models[0], Query(user="ghost", num=2))
    assert res3.item_scores == ()

    # num ≥ catalog size: every returned item must be real (regression for
    # the phantom id at n_items+1 escaping top-k)
    res4 = algos[0].predict(
        models[0], Query(user="nobody", num=10, recent_items=("i2",)),
    )
    names = {s.item for s in res4.item_scores}
    assert names <= {f"i{j}" for j in range(10)}


def test_sequence_engine_seq_parallel_config_path(seeded_sequence_app):
    """seq_parallel='ring' through engine params: the algorithm builds its
    own sp mesh (degree = largest divisor of max_len-1) and trains."""
    from incubator_predictionio_tpu.core import EngineParams
    from incubator_predictionio_tpu.models.sequence import (
        SeqRecAlgorithmParams, SequenceEngine,
    )
    from incubator_predictionio_tpu.models.sequence.engine import (
        DataSourceParams, PreparatorParams,
    )
    from incubator_predictionio_tpu.parallel.context import RuntimeContext

    engine = SequenceEngine().apply()
    params = EngineParams(
        data_source_params=("", DataSourceParams(app_name=seeded_sequence_app)),
        preparator_params=("", PreparatorParams(max_len=9)),  # train len 8
        algorithm_params_list=[
            ("sasrec", SeqRecAlgorithmParams(
                app_name=seeded_sequence_app, d_model=16, n_heads=2,
                n_layers=1, epochs=3, batch_size=16, seed=0,
                seq_parallel="ring",
            )),
        ],
    )
    models = engine.train(RuntimeContext(seed=0), params)
    assert models[0].final_loss > 0
