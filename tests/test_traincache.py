"""Training-projection cache (data/storage/traincache.py + cpplog wiring).

The contract under test: every scan served (even partially) from the cache
must be byte-identical to a fresh full native scan of the same log — same
triples, same first-seen id-table order — across creation at import time,
tail folds, time windows, deletes, and fallback shapes.
"""

import numpy as np
import pytest

from incubator_predictionio_tpu.data.datamap import DataMap
from incubator_predictionio_tpu.data.event import Event
from incubator_predictionio_tpu.data.storage import (
    StorageClientConfig,
    cpplog,
    traincache,
)
from incubator_predictionio_tpu.data.storage.base import Interactions
from incubator_predictionio_tpu.utils.times import from_millis

pytestmark = pytest.mark.skipif(
    __import__("incubator_predictionio_tpu.native", fromlist=["load"]).load()
    is None,
    reason="native library unavailable",
)


@pytest.fixture
def events(tmp_path, monkeypatch):
    # every log in these tests is "training scale"
    monkeypatch.setattr(traincache, "MIN_NNZ", 4)
    client = cpplog.StorageClient(
        StorageClientConfig(properties={"PATH": str(tmp_path)}))
    ev = cpplog.CppLogEvents(client, None, prefix="t_")
    yield ev
    client.close()


def _imp(events, app_id=1, n=8, t0=1_000_000, users=None, items=None):
    users = users if users is not None else np.arange(n, dtype=np.int32) % 3
    items = items if items is not None else np.arange(n, dtype=np.int32) % 4
    inter = Interactions(
        user_idx=np.asarray(users, np.int32),
        item_idx=np.asarray(items, np.int32),
        values=np.arange(1, len(users) + 1, dtype=np.float32),
        user_ids=[f"u{k}" for k in range(int(max(users)) + 1)],
        item_ids=[f"i{k}" for k in range(int(max(items)) + 1)],
    )
    assert events.import_interactions(
        inter, app_id, times=t0 + np.arange(len(users), dtype=np.int64),
    ) == len(users)
    return inter


def _scan(events, app_id=1, **kw):
    kw.setdefault("entity_type", "user")
    kw.setdefault("target_entity_type", "item")
    kw.setdefault("event_names", ("rate",))
    kw.setdefault("value_prop", "rating")
    return events.scan_interactions(app_id=app_id, **kw)


def _cache_path(events, app_id=1):
    return traincache.path_for(
        events.client._file(events.ns, app_id, None))


def _as_triples(inter):
    return [
        (inter.user_ids[int(u)], inter.item_ids[int(i)], float(v))
        for u, i, v in zip(inter.user_idx, inter.item_idx, inter.values)
    ]


def _assert_same(a, b):
    assert _as_triples(a) == _as_triples(b)
    assert list(a.user_ids) == list(b.user_ids)
    assert list(a.item_ids) == list(b.item_ids)


def _fresh_scan(events, app_id=1, **kw):
    """Ground truth: the same query with the cache removed."""
    _cache_path(events, app_id).unlink(missing_ok=True)
    out = _scan(events, app_id, **kw)
    return out


def test_import_creates_cache_and_scan_serves_it(events):
    _imp(events)
    assert _cache_path(events).exists()
    served = _scan(events)
    truth = _fresh_scan(events)
    _assert_same(served, truth)
    assert len(served) == 8


def test_cache_matches_scan_interning_order(events):
    # batch id tables deliberately hold unreferenced + shuffled ids: the
    # cache must still produce first-seen order (conformance contract)
    inter = Interactions(
        user_idx=np.array([2, 0, 2, 1], np.int32),
        item_idx=np.array([1, 1, 0, 2], np.int32),
        values=np.array([1, 2, 3, 4], np.float32),
        user_ids=["a", "b", "c", "never-used"],
        item_ids=["x", "y", "z"],
    )
    events.import_interactions(inter, 1, times=np.arange(4, dtype=np.int64))
    served = _scan(events)
    assert list(served.user_ids) == ["c", "a", "b"]
    assert list(served.item_ids) == ["y", "x", "z"]
    _assert_same(served, _fresh_scan(events))


def test_tail_fold_after_rest_ingest(events):
    _imp(events, t0=1000)
    # two REST-path events land past the cache's high-water mark
    for k, minutes in ((0, 10), (1, 11)):
        events.insert(Event(
            event="rate", entity_type="user", entity_id=f"new{k}",
            target_entity_type="item", target_entity_id="i0",
            properties=DataMap({"rating": 9.0 + k}),
            event_time=from_millis(1_000_000_000 + minutes)), 1)
    served = _scan(events)
    assert len(served) == 10
    assert "new0" in list(served.user_ids)
    _assert_same(served, _fresh_scan(events))
    # the fold advanced the cache: next scan serves 10 rows from cache
    cache = traincache.load(_cache_path(events))
    assert cache is not None and len(cache) == 10


def test_second_import_appends_to_cache(events):
    _imp(events, t0=1000)
    _imp(events, n=4, t0=500_000, users=np.array([3, 3, 0, 4]),
         items=np.array([0, 5, 1, 2]))
    cache = traincache.load(_cache_path(events))
    assert cache is not None and len(cache) == 12 and cache.raw_count == 12
    _assert_same(_scan(events), _fresh_scan(events))


def test_delete_invalidates_cache(events):
    _imp(events)
    ev_id = next(iter(events.find(app_id=1))).event_id
    assert events.delete(ev_id, 1)
    served = _scan(events)  # full scan (dead_count mismatch) + reseed
    assert len(served) == 7
    _assert_same(served, _fresh_scan(events))
    # the reseeded cache reflects the delete
    cache = traincache.load(_cache_path(events))
    assert cache is not None and len(cache) == 7


def test_time_window_served_from_cache(events):
    _imp(events, t0=1000)
    lo, hi = from_millis(1002), from_millis(1006)
    served = _scan(events, start_time=lo, until_time=hi)
    truth = _fresh_scan(events, start_time=lo, until_time=hi)
    assert len(served) == 4
    _assert_same(served, truth)


def test_non_servable_queries_bypass_cache(events):
    _imp(events)
    # fixed-value query: includes records regardless of the prop
    a = _scan(events, event_values={"rate": 2.5})
    assert set(a.values.tolist()) == {2.5}
    # no value_prop → default fill
    b = _scan(events, value_prop=None, default_value=7.0)
    assert set(b.values.tolist()) == {7.0}
    # two names
    c = _scan(events, event_names=("rate", "buy"))
    assert len(c) == 8


def test_out_of_order_tail_falls_back(events):
    _imp(events, t0=1_000_000)
    # REST event with an EARLIER event time than the cached rows
    events.insert(Event(
        event="rate", entity_type="user", entity_id="early",
        target_entity_type="item", target_entity_id="i0",
        properties=DataMap({"rating": 1.0}),
        event_time=from_millis(5)), 1)
    served = _scan(events)
    truth = _fresh_scan(events)
    assert _as_triples(served)[0][0] == "early"  # time order preserved
    _assert_same(served, truth)


def test_small_logs_get_no_cache(events, monkeypatch):
    monkeypatch.setattr(traincache, "MIN_NNZ", 1_000_000)
    _imp(events)
    assert not _cache_path(events).exists()
    assert len(_scan(events)) == 8


def test_corrupt_cache_is_ignored(events):
    _imp(events)
    path = _cache_path(events)
    path.write_bytes(path.read_bytes()[:40])  # torn file
    served = _scan(events)
    assert len(served) == 8
    _assert_same(served, _fresh_scan(events))


def test_drop_removes_cache(events):
    _imp(events)
    assert _cache_path(events).exists()
    events.remove(1)
    assert not _cache_path(events).exists()
