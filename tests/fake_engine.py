"""Fake DASE engine whose outputs encode params/ids — the controllable
fixture that lets tests assert exact train/eval wiring with no real ML.

Parity: core/src/test/.../controller/SampleEngine.scala:29-174 (Engine0
family: PDataSource0-4, PPreparator0-1, algorithms, serving).
"""

from __future__ import annotations

import dataclasses
from typing import List, Tuple

from incubator_predictionio_tpu.core import (
    Algorithm,
    DataSource,
    EmptyParams,
    Engine,
    Metric,
    Params,
    Preparator,
    SanityCheck,
    Serving,
)


@dataclasses.dataclass(frozen=True)
class DSP(Params):
    id: int = 0


@dataclasses.dataclass(frozen=True)
class PP(Params):
    id: int = 0


@dataclasses.dataclass(frozen=True)
class AP(Params):
    id: int = 0
    mult: int = 1


@dataclasses.dataclass(frozen=True)
class SP(Params):
    id: int = 0


@dataclasses.dataclass(frozen=True)
class TrainingData:
    ds_id: int


@dataclasses.dataclass(frozen=True)
class EvalInfo:
    ds_id: int
    ex: int


@dataclasses.dataclass(frozen=True)
class PreparedData:
    ds_id: int
    pp_id: int


@dataclasses.dataclass(frozen=True)
class Model:
    ds_id: int
    pp_id: int
    ap_id: int


@dataclasses.dataclass(frozen=True)
class Query:
    qx: int


@dataclasses.dataclass(frozen=True)
class Prediction:
    model: Model
    qx: int
    supplemented: bool = False


@dataclasses.dataclass(frozen=True)
class Actual:
    qx: int


class DataSource0(DataSource):
    """Training + n_eval eval sets with n_q queries each."""

    def __init__(self, params: DSP = DSP()):
        super().__init__(params)

    def read_training(self, ctx) -> TrainingData:
        return TrainingData(ds_id=self.params.id)

    def read_eval(self, ctx):
        out = []
        for ex in range(2):
            qas = [(Query(qx), Actual(qx)) for qx in range(3)]
            out.append((TrainingData(self.params.id), EvalInfo(self.params.id, ex), qas))
        return out


class FailingDataSource(DataSource):
    def read_training(self, ctx):
        raise RuntimeError("data source boom")


class SanityFailDataSource(DataSource):
    class TD(SanityCheck):
        def sanity_check(self) -> None:
            raise ValueError("sanity failed")

    def read_training(self, ctx):
        return SanityFailDataSource.TD()


class NoArgDataSource(DataSource):
    """Has a no-arg constructor — exercises the Doer fallback path."""

    def __init__(self):
        super().__init__(EmptyParams())

    def read_training(self, ctx) -> TrainingData:
        return TrainingData(ds_id=-99)


class Preparator0(Preparator):
    def __init__(self, params: PP = PP()):
        super().__init__(params)

    def prepare(self, ctx, td: TrainingData) -> PreparedData:
        return PreparedData(ds_id=td.ds_id, pp_id=self.params.id)


class Algorithm0(Algorithm):
    def __init__(self, params: AP = AP()):
        super().__init__(params)

    def train(self, ctx, pd: PreparedData) -> Model:
        return Model(ds_id=pd.ds_id, pp_id=pd.pp_id, ap_id=self.params.id)

    def predict(self, model: Model, query: Query) -> Prediction:
        return Prediction(model=model, qx=query.qx)


class Algorithm1(Algorithm):
    def __init__(self, params: AP = AP()):
        super().__init__(params)

    def train(self, ctx, pd: PreparedData) -> Model:
        return Model(ds_id=pd.ds_id, pp_id=pd.pp_id, ap_id=100 + self.params.id)

    def predict(self, model: Model, query: Query) -> Prediction:
        return Prediction(model=model, qx=query.qx)


class Serving0(Serving):
    def __init__(self, params: SP = SP()):
        super().__init__(params)

    def serve(self, query: Query, predictions) -> Prediction:
        # first prediction wins; encode how many came in via qx passthrough
        return predictions[0]


class SupplementServing(Serving):
    """Marks queries as supplemented; serve asserts algorithms saw the mark."""

    def supplement(self, query: Query) -> Query:
        return Query(qx=query.qx + 1000)

    def serve(self, query: Query, predictions) -> Prediction:
        # query must be the ORIGINAL (unsupplemented) one here
        assert query.qx < 1000, "serve must receive the original query"
        return predictions[0]


def make_engine() -> Engine:
    return Engine(
        DataSource0,
        Preparator0,
        {"algo0": Algorithm0, "algo1": Algorithm1},
        Serving0,
    )


def params(ds=1, pp=2, algos=(("algo0", AP(3)),), sp=4):
    from incubator_predictionio_tpu.core import EngineParams

    return EngineParams(
        data_source_params=("", DSP(ds)),
        preparator_params=("", PP(pp)),
        algorithm_params_list=list(algos),
        serving_params=("", SP(sp)),
    )


class QxMetric(Metric):
    """Deterministic metric: mean of (prediction.model.ap_id)."""

    def calculate(self, ctx, eval_data_set) -> float:
        scores = [
            p.model.ap_id for _info, qpas in eval_data_set for _q, p, _a in qpas
        ]
        return sum(scores) / len(scores) if scores else float("nan")
