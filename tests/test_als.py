"""ALS solver correctness on the CPU mesh."""

import numpy as np
import pytest

from incubator_predictionio_tpu.ops import (
    als_train,
    build_padded_rows,
    rmse,
    top_k_with_exclusions,
)


def synthetic_ratings(n_users=60, n_items=40, rank=4, density=0.3, seed=0):
    rng = np.random.default_rng(seed)
    u = rng.normal(size=(n_users, rank)) / np.sqrt(rank)
    v = rng.normal(size=(n_items, rank)) / np.sqrt(rank)
    full = u @ v.T + 3.0
    mask = rng.random((n_users, n_items)) < density
    users, items = np.nonzero(mask)
    return users, items, full[users, items].astype(np.float32)


def test_build_padded_rows_round_trip():
    users = np.array([0, 0, 0, 1, 2, 2, 2, 2, 2])
    items = np.array([5, 6, 7, 1, 0, 1, 2, 3, 4])
    vals = np.arange(9, dtype=np.float32)
    buckets = build_padded_rows(users, items, vals, n_rows=3, min_width=2,
                                row_multiple=1)
    # reconstruct
    seen = {}
    for b in buckets:
        for i, rid in enumerate(b.row_ids):
            if rid < 0:
                continue
            cols = b.cols[i][b.mask[i] > 0]
            vs = b.vals[i][b.mask[i] > 0]
            seen.setdefault(int(rid), []).extend(zip(cols.tolist(), vs.tolist()))
    assert sorted(seen[0]) == [(5, 0.0), (6, 1.0), (7, 2.0)]
    assert seen[1] == [(1, 3.0)]
    assert len(seen[2]) == 5


def test_build_padded_rows_splits_heavy_rows():
    users = np.zeros(10, dtype=np.int64)
    items = np.arange(10)
    vals = np.ones(10, np.float32)
    buckets = build_padded_rows(users, items, vals, 1, min_width=2,
                                max_width=4, row_multiple=1)
    total = sum(int(b.mask.sum()) for b in buckets)
    assert total == 10  # nothing dropped
    widths = sorted(b.width for b in buckets)
    assert max(widths) <= 4


def test_als_fits_synthetic_low_rank():
    users, items, ratings = synthetic_ratings()
    state, history = als_train(
        users, items, ratings, n_users=60, n_items=40,
        rank=8, iterations=8, l2=0.01, track_rmse=True,
    )
    assert history[-1] < 0.15  # near-exact recovery of a rank-4 matrix
    assert history[-1] <= history[0]  # monotone-ish improvement end to end
    assert rmse(state, users, items, ratings) == pytest.approx(history[-1])


def test_als_mixed_bf16_schedule_recovers_planted_rank():
    """bf16 early sweeps + f32 polish land on the same fixed point as the
    all-f32 run: ALS re-solves every row from scratch each half-sweep, so
    low-precision sweeps only change the polish's starting point. Guards
    the bench's mixed schedule (bench.py PIO_BENCH_BF16_SWEEPS)."""
    users, items, ratings = synthetic_ratings(
        n_users=80, n_items=50, rank=4, density=0.4, seed=3)
    f32, _ = als_train(users, items, ratings, 80, 50, rank=8,
                       iterations=8, l2=0.01, seed=5)
    mixed, _ = als_train(users, items, ratings, 80, 50, rank=8,
                         iterations=8, l2=0.01, seed=5, bf16_sweeps=6)
    r_f32 = rmse(f32, users, items, ratings)
    r_mixed = rmse(mixed, users, items, ratings)
    # near-exact recovery of the planted rank-4 structure, both schedules
    assert r_f32 < 0.15
    assert r_mixed < r_f32 + 0.02  # parity: polish restores convergence
    # all-bf16 (no polish) is the documented degraded mode — it must still
    # produce finite factors, but is NOT required to reach parity
    nopolish, _ = als_train(users, items, ratings, 80, 50, rank=8,
                            iterations=8, l2=0.01, seed=5, bf16_sweeps=8)
    assert np.isfinite(np.asarray(nopolish.user_factors)).all()


def test_als_f32_path_and_reg_modes():
    import jax.numpy as jnp

    users, items, ratings = synthetic_ratings(seed=1)
    state, _ = als_train(
        users, items, ratings, 60, 40, rank=8, iterations=4,
        compute_dtype=jnp.float32, reg_nnz=False,
    )
    assert rmse(state, users, items, ratings) < 0.5


def test_als_cold_rows_stay_zero():
    # user 59 and item 39 have no ratings
    users = np.array([0, 1, 2])
    items = np.array([0, 1, 2])
    ratings = np.array([4.0, 3.0, 5.0], np.float32)
    state, _ = als_train(users, items, ratings, 60, 40, rank=4, iterations=2)
    assert np.allclose(np.asarray(state.user_factors)[59], 0.0)
    assert np.allclose(np.asarray(state.item_factors)[39], 0.0)


def test_als_heavy_row_trains_and_sweep_api_still_rejects():
    users = np.zeros(10, dtype=np.int64)
    items = np.arange(10)
    ratings = np.ones(10, np.float32)
    # als_train routes split rows through the partial-Gram combining solver
    state, _ = als_train(users, items, ratings, 1, 10, rank=2, iterations=1,
                         max_width=4)
    assert np.isfinite(np.asarray(state.user_factors)).all()
    # the raw sweep API cannot combine split rows and must keep rejecting
    from incubator_predictionio_tpu.ops.als import als_init, als_sweep
    from incubator_predictionio_tpu.ops.sparse import build_padded_rows
    import jax
    buckets = build_padded_rows(users, items, ratings, 1, max_width=4)
    with pytest.raises(NotImplementedError):
        als_sweep(als_init(jax.random.key(0), 1, 10, 2), buckets, buckets)


def test_top_k_with_exclusions():
    import jax.numpy as jnp

    scores = jnp.asarray([1.0, 5.0, 3.0, 4.0, 2.0])
    top_s, top_i = top_k_with_exclusions(scores, 2)
    assert top_i.tolist() == [1, 3]
    top_s, top_i = top_k_with_exclusions(
        scores, 2, exclude=jnp.asarray([1, 3], jnp.int32)
    )
    assert top_i.tolist() == [2, 4]
    allowed = jnp.asarray([True, False, True, True, True])
    top_s, top_i = top_k_with_exclusions(scores, 2, allowed_mask=allowed)
    assert top_i.tolist() == [3, 2]
    # -1 exclude ids are inert (drop mode)
    _s, top_i = top_k_with_exclusions(scores, 1, exclude=jnp.asarray([-1]))
    assert top_i.tolist() == [1]


class TestSplitRowSolver:
    """Rows with degree > max_width: partial-Gram combining (ALX-style)."""

    def test_explicit_matches_unsplit(self):
        import numpy as np
        from incubator_predictionio_tpu.ops.als import als_train, rmse
        rng = np.random.default_rng(0)
        # user 0 rates 60 items; max_width=16 forces 4-way splitting
        users = np.concatenate([np.zeros(60, np.int64),
                                rng.integers(1, 20, 200)])
        items = np.concatenate([np.arange(60) % 30,
                                rng.integers(0, 30, 200)]).astype(np.int64)
        ratings = rng.integers(1, 6, 260).astype(np.float32)
        split, _ = als_train(users, items, ratings, 20, 30, rank=8,
                             iterations=5, seed=1, max_width=16)
        whole, _ = als_train(users, items, ratings, 20, 30, rank=8,
                             iterations=5, seed=1, max_width=1 << 12)
        np.testing.assert_allclose(
            np.asarray(split.user_factors), np.asarray(whole.user_factors),
            atol=1e-4)
        np.testing.assert_allclose(
            np.asarray(split.item_factors), np.asarray(whole.item_factors),
            atol=1e-4)
        assert rmse(split, users, items, ratings) < 1.0

    def test_implicit_matches_unsplit(self):
        import numpy as np
        from incubator_predictionio_tpu.ops.als import als_train_implicit
        rng = np.random.default_rng(2)
        users = np.concatenate([np.full(40, 3, np.int64),
                                rng.integers(0, 10, 100)])
        items = np.concatenate([np.arange(40) % 25,
                                rng.integers(0, 25, 100)]).astype(np.int64)
        w = rng.random(140).astype(np.float32) + 0.5
        split = als_train_implicit(users, items, w, 10, 25, rank=8,
                                   iterations=4, seed=3, max_width=8)
        whole = als_train_implicit(users, items, w, 10, 25, rank=8,
                                   iterations=4, seed=3, max_width=1 << 12)
        np.testing.assert_allclose(
            np.asarray(split.user_factors), np.asarray(whole.user_factors),
            atol=1e-4)

    def test_split_heavy_structure(self):
        import numpy as np
        from incubator_predictionio_tpu.ops.sparse import (
            build_padded_rows, split_heavy)
        rows = np.concatenate([np.zeros(20, np.int64), [1, 2, 2]])
        cols = np.arange(23, dtype=np.int32)
        vals = np.ones(23, np.float32)
        buckets = build_padded_rows(rows, cols, vals, 3, max_width=8)
        light, heavy = split_heavy(buckets)
        assert heavy is not None
        # row 0 split into ceil(20/8)=3 segments; rows 1, 2 stay light
        assert list(heavy.row_ids) == [0]
        assert heavy.seg_ids.shape[0] == 3
        assert heavy.mask.sum() == 20
        light_ids = np.concatenate([b.row_ids for b in light])
        assert set(light_ids[light_ids >= 0]) == {1, 2}
        # no-split input passes through untouched
        l2, h2 = split_heavy(build_padded_rows(
            rows[20:], cols[20:], vals[20:], 3))
        assert h2 is None and len(l2) == 1
