"""ALS solver correctness on the CPU mesh."""

import numpy as np
import pytest

from incubator_predictionio_tpu.ops import (
    als_train,
    build_padded_rows,
    rmse,
    top_k_with_exclusions,
)


def synthetic_ratings(n_users=60, n_items=40, rank=4, density=0.3, seed=0):
    rng = np.random.default_rng(seed)
    u = rng.normal(size=(n_users, rank)) / np.sqrt(rank)
    v = rng.normal(size=(n_items, rank)) / np.sqrt(rank)
    full = u @ v.T + 3.0
    mask = rng.random((n_users, n_items)) < density
    users, items = np.nonzero(mask)
    return users, items, full[users, items].astype(np.float32)


def test_build_padded_rows_round_trip():
    users = np.array([0, 0, 0, 1, 2, 2, 2, 2, 2])
    items = np.array([5, 6, 7, 1, 0, 1, 2, 3, 4])
    vals = np.arange(9, dtype=np.float32)
    buckets = build_padded_rows(users, items, vals, n_rows=3, min_width=2,
                                row_multiple=1)
    # reconstruct
    seen = {}
    for b in buckets:
        for i, rid in enumerate(b.row_ids):
            if rid < 0:
                continue
            cols = b.cols[i][b.mask[i] > 0]
            vs = b.vals[i][b.mask[i] > 0]
            seen.setdefault(int(rid), []).extend(zip(cols.tolist(), vs.tolist()))
    assert sorted(seen[0]) == [(5, 0.0), (6, 1.0), (7, 2.0)]
    assert seen[1] == [(1, 3.0)]
    assert len(seen[2]) == 5


def test_build_padded_rows_splits_heavy_rows():
    users = np.zeros(10, dtype=np.int64)
    items = np.arange(10)
    vals = np.ones(10, np.float32)
    buckets = build_padded_rows(users, items, vals, 1, min_width=2,
                                max_width=4, row_multiple=1)
    total = sum(int(b.mask.sum()) for b in buckets)
    assert total == 10  # nothing dropped
    widths = sorted(b.width for b in buckets)
    assert max(widths) <= 4


def test_als_fits_synthetic_low_rank():
    users, items, ratings = synthetic_ratings()
    state, history = als_train(
        users, items, ratings, n_users=60, n_items=40,
        rank=8, iterations=8, l2=0.01, track_rmse=True,
    )
    assert history[-1] < 0.15  # near-exact recovery of a rank-4 matrix
    assert history[-1] <= history[0]  # monotone-ish improvement end to end
    assert rmse(state, users, items, ratings) == pytest.approx(history[-1])


def test_als_f32_path_and_reg_modes():
    import jax.numpy as jnp

    users, items, ratings = synthetic_ratings(seed=1)
    state, _ = als_train(
        users, items, ratings, 60, 40, rank=8, iterations=4,
        compute_dtype=jnp.float32, reg_nnz=False,
    )
    assert rmse(state, users, items, ratings) < 0.5


def test_als_cold_rows_stay_zero():
    # user 59 and item 39 have no ratings
    users = np.array([0, 1, 2])
    items = np.array([0, 1, 2])
    ratings = np.array([4.0, 3.0, 5.0], np.float32)
    state, _ = als_train(users, items, ratings, 60, 40, rank=4, iterations=2)
    assert np.allclose(np.asarray(state.user_factors)[59], 0.0)
    assert np.allclose(np.asarray(state.item_factors)[39], 0.0)


def test_als_heavy_row_raises():
    users = np.zeros(10, dtype=np.int64)
    items = np.arange(10)
    ratings = np.ones(10, np.float32)
    with pytest.raises(NotImplementedError):
        als_train(users, items, ratings, 1, 10, rank=2, iterations=1,
                  max_width=4)


def test_top_k_with_exclusions():
    import jax.numpy as jnp

    scores = jnp.asarray([1.0, 5.0, 3.0, 4.0, 2.0])
    top_s, top_i = top_k_with_exclusions(scores, 2)
    assert top_i.tolist() == [1, 3]
    top_s, top_i = top_k_with_exclusions(
        scores, 2, exclude=jnp.asarray([1, 3], jnp.int32)
    )
    assert top_i.tolist() == [2, 4]
    allowed = jnp.asarray([True, False, True, True, True])
    top_s, top_i = top_k_with_exclusions(scores, 2, allowed_mask=allowed)
    assert top_i.tolist() == [3, 2]
    # -1 exclude ids are inert (drop mode)
    _s, top_i = top_k_with_exclusions(scores, 1, exclude=jnp.asarray([-1]))
    assert top_i.tolist() == [1]
