"""Distributed communication backend: collectives + pod mesh construction."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from incubator_predictionio_tpu.parallel import collectives as C
from incubator_predictionio_tpu.parallel.collectives import shard_map
from incubator_predictionio_tpu.parallel.distributed import (
    ensure_initialized,
    host_local_batch_slice,
    make_pod_mesh,
)


def _mesh1d(name="dp", n=8):
    return Mesh(np.array(jax.devices()[:n]), (name,))


def _run(mesh, fn, x, in_spec, out_spec):
    return shard_map(fn, mesh=mesh, in_specs=(in_spec,), out_specs=out_spec,
                     check_vma=False)(x)


def test_all_reduce_sum_mean_max():
    mesh = _mesh1d()
    x = jnp.arange(8.0)

    out = _run(mesh, lambda v: C.all_reduce_sum(v, "dp"), x, P("dp"), P("dp"))
    np.testing.assert_allclose(np.asarray(out), np.full(8, x.sum()))
    out = _run(mesh, lambda v: C.all_reduce_mean(v, "dp"), x, P("dp"), P("dp"))
    np.testing.assert_allclose(np.asarray(out), np.full(8, x.mean()))
    out = _run(mesh, lambda v: C.all_reduce_max(v, "dp"), x, P("dp"), P("dp"))
    np.testing.assert_allclose(np.asarray(out), np.full(8, 7.0))


def test_all_gather_and_reduce_scatter():
    mesh = _mesh1d()
    x = jnp.arange(16.0)

    gathered = _run(mesh, lambda v: C.all_gather(v, "dp"), x, P("dp"), P("dp"))
    # every shard holds the full row → global result is 8 copies
    assert gathered.shape == (128,)
    np.testing.assert_allclose(np.asarray(gathered)[:16], np.arange(16.0))

    scattered = _run(mesh, lambda v: C.reduce_scatter(v, "dp"),
                     jnp.ones(64), P("dp"), P("dp"))
    # each shard's [8] local vector sums across shards then scatters one
    # element back per shard: every element is 8
    np.testing.assert_allclose(np.asarray(scattered), np.full(8, 8.0))


def test_ppermute_ring_rotation():
    mesh = _mesh1d()
    x = jnp.arange(8.0)
    nxt = _run(mesh, lambda v: C.ppermute_next(v, "dp"), x, P("dp"), P("dp"))
    np.testing.assert_allclose(np.asarray(nxt), np.roll(np.arange(8.0), 1))
    prv = _run(mesh, lambda v: C.ppermute_prev(v, "dp"), x, P("dp"), P("dp"))
    np.testing.assert_allclose(np.asarray(prv), np.roll(np.arange(8.0), -1))


def test_broadcast_from():
    mesh = _mesh1d()
    x = jnp.arange(8.0)
    out = _run(mesh, lambda v: C.broadcast_from(v, "dp", src_index=3),
               x, P("dp"), P("dp"))
    np.testing.assert_allclose(np.asarray(out), np.full(8, 3.0))


def test_all_to_all_transpose():
    mesh = _mesh1d(n=4)
    x = jnp.arange(16.0).reshape(4, 4)

    def body(v):  # local [1, 4] → split cols, gather rows → [4, 1]
        return C.all_to_all(v, "dp", split_axis=1, concat_axis=0)

    out = _run(mesh, body, x, P("dp", None), P(None, "dp"))
    np.testing.assert_allclose(np.asarray(out), np.asarray(x).T.T)
    assert out.shape == (4, 4)


def test_make_pod_mesh_shapes():
    mesh = make_pod_mesh(("dp", "mp"), (2, 4))
    assert dict(mesh.shape) == {"dp": 2, "mp": 4}
    mesh = make_pod_mesh(("dp", "sp"), (-1, 2))
    assert dict(mesh.shape) == {"dp": 4, "sp": 2}
    with pytest.raises(ValueError):
        make_pod_mesh(("dp",), (3,))


def test_single_host_runtime():
    assert ensure_initialized() is False  # no coordinator configured
    assert host_local_batch_slice(64) == slice(0, 64)


def test_dp_training_step_gradient_sync():
    """The DP pattern every engine uses: per-shard grads, pmean, identical
    update everywhere — Spark's aggregate replaced by one all-reduce."""
    mesh = _mesh1d()
    w = jnp.ones(4)
    x = jnp.arange(32.0).reshape(8, 4)

    @functools.partial(shard_map, mesh=mesh, in_specs=(P(), P("dp", None)),
                       out_specs=P(), check_vma=False)
    def grad_step(w, batch):
        g = jax.grad(lambda w: jnp.mean((batch @ w) ** 2))(w)
        return C.all_reduce_mean(g, "dp")

    g = grad_step(w, x)
    g_ref = jax.grad(lambda w: jnp.mean((x @ w) ** 2))(w)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), rtol=1e-6)
