"""SelfCleaningDataSource behavior (parity: core/SelfCleaningDataSource.scala)."""

from datetime import timedelta

import pytest

from incubator_predictionio_tpu.core.self_cleaning import (
    EventWindow,
    SelfCleaningDataSource,
    compress_properties,
    parse_duration,
)
from incubator_predictionio_tpu.data.datamap import DataMap
from incubator_predictionio_tpu.data.event import Event
from incubator_predictionio_tpu.data.storage import App, Storage
from incubator_predictionio_tpu.utils.times import now_utc


def test_parse_duration():
    assert parse_duration("30 days") == timedelta(days=30)
    assert parse_duration("3600s") == timedelta(seconds=3600)
    assert parse_duration("2h") == timedelta(hours=2)
    assert parse_duration(90) == timedelta(seconds=90)
    assert parse_duration(timedelta(minutes=1)) == timedelta(minutes=1)
    with pytest.raises(ValueError):
        parse_duration("banana")


def ev(name, eid, minutes_ago, props=None, target=None):
    return Event(
        event=name,
        entity_type="user",
        entity_id=eid,
        target_entity_type="item" if target else None,
        target_entity_id=target,
        properties=DataMap(props or {}),
        event_time=now_utc() - timedelta(minutes=minutes_ago),
    )


def test_compress_set_chains():
    events = [
        ev("$set", "u1", 30, {"a": 1, "b": "old"}),
        ev("$set", "u1", 20, {"b": "new"}),
        ev("$unset", "u1", 10, {"a": None}),
        ev("rate", "u1", 5, {"r": 4}, target="i1"),
        ev("$set", "u2", 15, {"x": 1}),
    ]
    out = compress_properties(events)
    sets = [e for e in out if e.event == "$set"]
    assert len(sets) == 2
    u1_set = next(e for e in sets if e.entity_id == "u1")
    assert u1_set.properties.fields == {"a": 1, "b": "new"}
    # $unset and plain events pass through
    assert sum(1 for e in out if e.event == "$unset") == 1
    assert sum(1 for e in out if e.event == "rate") == 1


class CleaningSource(SelfCleaningDataSource):
    def __init__(self, app_name, window):
        self.app_name = app_name
        self.event_window = window


@pytest.fixture
def mem_storage():
    Storage.configure({
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    })
    yield
    Storage.reset()


def test_clean_persisted_events(mem_storage):
    apps = Storage.get_meta_data_apps()
    app_id = apps.insert(App(0, "cleanapp"))
    dao = Storage.get_events()
    dao.insert(ev("$set", "u1", minutes_ago=60 * 24 * 40, props={"stale": 1}), app_id)
    dao.insert(ev("$set", "u1", minutes_ago=30, props={"a": 1}), app_id)
    dao.insert(ev("$set", "u1", minutes_ago=20, props={"b": 2}), app_id)
    dao.insert(ev("rate", "u1", minutes_ago=10, props={"r": 5}, target="i1"), app_id)
    dup = ev("buy", "u1", minutes_ago=9, target="i2")
    dao.insert(dup, app_id)
    dao.insert(dup.with_id(None), app_id)  # duplicate content, new id

    src = CleaningSource(
        "cleanapp",
        EventWindow(duration="30 days", remove_duplicates=True,
                    compress_properties=True),
    )
    n = src.clean_persisted_events()
    remaining = list(dao.find(app_id=app_id))
    assert n == len(remaining) == 3  # merged $set + rate + one buy
    merged = next(e for e in remaining if e.event == "$set")
    assert merged.properties.fields == {"a": 1, "b": 2}  # stale event dropped
    assert sum(1 for e in remaining if e.event == "buy") == 1


def test_no_window_is_noop(mem_storage):
    apps = Storage.get_meta_data_apps()
    app_id = apps.insert(App(0, "noopapp"))
    dao = Storage.get_events()
    dao.insert(ev("rate", "u1", 5, target="i1"), app_id)
    src = CleaningSource("noopapp", None)
    assert src.clean_persisted_events() == 0
    assert len(list(dao.find(app_id=app_id))) == 1
