"""MIPS catalogue lifecycle: PQ residual codes, the background
rebuild-and-swap, and host-tiered cold buckets (ops/mips.py +
ops/mips_daemon.py).

The pins, in the order the ISSUE promises them:

- PQ-vs-exhaustive recall parity at every ``PIO_SERVE_MIPS_PQ_M`` on a
  small planted catalogue (full probe, so the parity statement is about
  the residual codes, not the probe budget), plus the divisor snap;
- the ``adopt_index`` age-baseline reset regression (a hot-swapped
  index must never report as stale) on a fake clock, and the same
  reset through a rebuild swap;
- rebuild-under-serve correctness: every overlay-published key is
  findable at recall 1.0 before AND after the atomic swap, a known-row
  override survives, the old index object still serves (in-flight
  queries finish on the old arrays), and a publish that races the swap
  re-routes to the successor;
- cold-bucket tiering: rebuild demotes unprobed buckets to a host
  mini-index, cold rows stay findable through the merged host stage,
  probe pressure books ``cold.hits``, and a promote-triggered rebuild
  brings the pressured rows back to device;
- the daemon: trigger readers and ``check_trigger`` ordering,
  ``sweep_now`` folding a planted tail through a real rebuild under
  its own trace, refcounted acquire/release lifecycle;
- the exhaustive-fallback merge: published rows are visible on every
  fallback route (mode off, big exclude, batch path) — EXCEPT masked
  queries, where a virtual id cannot honor an item mask.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from incubator_predictionio_tpu.obs import metrics as obs_metrics
from incubator_predictionio_tpu.ops import mips, mips_daemon, topk
from incubator_predictionio_tpu.utils.planted import (
    exhaustive_top_k,
    planted_item_factors,
    planted_queries,
    recall_against_oracle,
)

N_ITEMS, RANK, K = 4096, 32, 10


@pytest.fixture(scope="module")
def planted():
    vf = planted_item_factors(N_ITEMS, RANK, seed=13)
    queries = planted_queries(vf, 8, seed=17)
    return vf, queries


@pytest.fixture
def mips_on(monkeypatch):
    monkeypatch.setenv("PIO_SERVE_MIPS", "on")


def _dominating(rng, n):
    """Fresh publish vectors whose self-score beats every base row —
    recall 1.0 on them is then a statement about the plumbing, not
    about probe luck."""
    v = rng.normal(size=(n, RANK)).astype(np.float32)
    v /= np.linalg.norm(v, axis=1, keepdims=True)
    return v * 8.0


def _top_ids(table, q, **kw):
    packed = np.asarray(topk.score_and_top_k(jnp.asarray(q), table,
                                             k=K, **kw))
    return packed[1].astype(np.int64).tolist()


# ---------------------------------------------------------------------------
# PQ residual codes
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("m", [4, 8, 16, 32])
def test_pq_recall_parity_at_every_m(planted, mips_on, monkeypatch, m):
    """Asymmetric PQ-over-residuals must hold the exhaustive recall
    gate at every registered subquantizer count. Full probe isolates
    the codes: any miss is the coarse PQ ranking dropping a true
    top-k row past the exact-rerank width."""
    monkeypatch.setenv("PIO_SERVE_MIPS_QUANT", "pq")
    monkeypatch.setenv("PIO_SERVE_MIPS_PQ_M", str(m))
    monkeypatch.setenv("PIO_SERVE_MIPS_NPROBE", str(N_ITEMS))
    vf, queries = planted
    table = jax.device_put(vf)
    index = mips.build_index(table, N_ITEMS, seed=13)
    assert index.quant == "pq"
    assert index.pq_m == m
    assert np.asarray(index.pq_books).shape == (m, 256, RANK // m)

    oracle = exhaustive_top_k(vf, queries, K)
    got = np.stack([
        np.asarray(mips.mips_score_and_top_k(q, table, index, K))[1]
        .astype(np.int64) for q in queries])
    recall, worst = recall_against_oracle(got, oracle, K)
    assert recall >= 0.95, (m, recall, worst)


def test_pq_m_snaps_down_to_a_rank_divisor(monkeypatch):
    """A knob step that lands on a non-divisor must degrade to the next
    divisor below, never crash a rebuild."""
    monkeypatch.setenv("PIO_SERVE_MIPS_PQ_M", "24")
    assert mips._pq_m(32) == 16
    monkeypatch.setenv("PIO_SERVE_MIPS_PQ_M", "7")
    assert mips._pq_m(32) == 4
    monkeypatch.delenv("PIO_SERVE_MIPS_PQ_M")
    assert mips._pq_m(32) == 16                    # default
    assert mips._pq_m(8) == 8                      # clamped to rank


# ---------------------------------------------------------------------------
# the age baseline (adopt + rebuild both reset it)
# ---------------------------------------------------------------------------

def test_adopt_and_rebuild_reset_the_age_baseline(planted, mips_on,
                                                  monkeypatch):
    """pio_mips_index_age_seconds must never report a hot-swapped index
    as stale: adopt_index (deploy-time table adoption) and the daemon's
    rebuild swap both reset ``built_at`` through the _now() seam."""
    t = {"now": 1000.0}
    monkeypatch.setattr(mips, "_now", lambda: t["now"])
    vf, _queries = planted
    table = jax.device_put(vf)
    index = mips.build_index(table, N_ITEMS, seed=13)
    assert index.built_at == 1000.0

    t["now"] = 1600.0
    table2 = jax.device_put(vf.copy())
    assert mips.adopt_index(table, table2) is index
    assert mips.index_for(table2) is index
    # the regression this pins: before the fix, adoption kept the OLD
    # build stamp and a freshly deployed model reported 600s of age
    assert index.built_at == 1600.0
    mips._collect_index_age()
    age = obs_metrics.REGISTRY.get("pio_mips_index_age_seconds")
    assert age.value == pytest.approx(0.0)

    t["now"] = 2500.0
    new = mips.rebuild_index(table2, trigger="manual")
    assert new is not None and new is not index
    assert mips.index_for(table2) is new
    assert new.built_at == 2500.0


# ---------------------------------------------------------------------------
# rebuild-under-serve: the swap choreography
# ---------------------------------------------------------------------------

def test_rebuild_swap_preserves_every_published_key(planted, mips_on):
    vf, _queries = planted
    table = jax.device_put(vf.copy())
    old = mips.build_index(table, N_ITEMS, seed=13)
    rng = np.random.default_rng(23)
    fresh = _dominating(rng, 24)
    vids = mips.publish_rows(table, fresh)
    assert vids is not None and (vids >= old.capacity).all()
    # known-row override: the published solve replaces the base row
    row = 99
    override = _dominating(rng, 1)[0]
    mips.publish_rows(table, override[None, :], rows=[row])

    # before: recall 1.0 on every published key (exact tail)
    for i, vid in enumerate(vids):
        assert _top_ids(table, fresh[i])[0] == int(vid)
    assert _top_ids(table, override)[0] == row

    new = mips.rebuild_index(table, trigger="tail", probe_recall=True)
    assert new is not None and new is not old
    assert mips.index_for(table) is new
    assert old._superseded is new
    # the tail folded into the dense ext block at the SAME ids — the
    # overlay's key→id map survives the swap untouched
    assert new.tail_virtual_size() == 0
    assert new.n_ext >= len(vids)

    # after: recall 1.0 on every key, now served from device ext rows
    for i, vid in enumerate(vids):
        ids = _top_ids(table, fresh[i])
        assert ids[0] == int(vid), (i, ids)
    assert _top_ids(table, override)[0] == row
    # in-flight queries holding the OLD index object finish on the old
    # arrays (the swap never mutates them)
    got_old = np.asarray(
        mips.mips_score_and_top_k(fresh[0], table, old, K))
    assert int(got_old[1][0]) == int(vids[0])

    # a publish racing the swap (publisher resolved the OLD index
    # before the registry flipped) re-routes to the successor
    late = _dominating(rng, 1)
    orig_index_for = mips.index_for
    mips.index_for = lambda _t: old
    try:
        (late_vid,) = mips.publish_rows(table, late)
    finally:
        mips.index_for = orig_index_for
    assert new.tail_virtual_size() == 1          # landed on NEW
    assert _top_ids(table, late[0])[0] == int(late_vid)

    # the rebuild counter booked its trigger
    reb = obs_metrics.REGISTRY.get("pio_mips_rebuilds_total")
    assert reb.labels(trigger="tail").value >= 1


def test_back_to_back_rebuilds_reuse_compiled_shapes(planted, mips_on):
    """The ext block's pow2 rung: consecutive rebuilds with a same-rung
    tail produce identical device shapes, so the steady churn cycle
    (publish → rebuild → publish → rebuild) compiles NOTHING after the
    first swap's warmup."""
    vf, queries = planted
    table = jax.device_put(vf.copy())
    mips.build_index(table, N_ITEMS, seed=13)
    rng = np.random.default_rng(29)
    mips.publish_rows(table, _dominating(rng, 12))
    mips.rebuild_index(table, trigger="tail")
    _top_ids(table, queries[0])                  # warm the serve path
    warm = mips.mips_compile_cache_size()
    # stay inside the ext block's pow2 rung (12 → 14 → 16 pads to 16):
    # the shapes the swap publishes are bit-identical, so the churn
    # cycle compiles nothing
    for _ in range(2):
        mips.publish_rows(table, _dominating(rng, 2))
        mips.rebuild_index(table, trigger="tail")
        _top_ids(table, queries[0])
    assert mips.mips_compile_cache_size() == warm


# ---------------------------------------------------------------------------
# host-tiered cold buckets
# ---------------------------------------------------------------------------

def test_cold_tier_demote_serve_and_promote(planted, mips_on,
                                            monkeypatch):
    monkeypatch.setenv("PIO_MIPS_TIER", "on")
    vf, _queries = planted
    table = jax.device_put(vf.copy())
    index = mips.build_index(table, N_ITEMS, seed=13)
    # plant the probe-hit profile the sampler would have produced: a
    # quarter of the buckets never probed over the sample window
    index.probe_hits[:] = 1
    index.probe_hits[: index.c_total // 4] = 0
    index._probe_samples = 10_000

    new = mips.rebuild_index(table, trigger="manual")
    assert new is not None and new.cold is not None
    assert new.cold.rows > 0
    dev, host = new.tier_rows()
    assert host == new.cold.rows
    assert dev + host == N_ITEMS
    mips._collect_index_age()
    tier = obs_metrics.REGISTRY.get("pio_mips_tier_rows")
    assert tier.labels(tier="host").value >= new.cold.rows

    # a cold row that is its own best match must still be findable —
    # served by the host mini-index merged into the device result
    cold_ids = np.concatenate(
        [ids for ids in new.cold.member_ids if len(ids)])
    cold_id = next(int(c) for c in cold_ids[:256]
                   if int(np.argmax(vf @ vf[int(c)])) == int(c))
    ids = _top_ids(table, vf[cold_id])
    assert ids[0] == cold_id, ids
    # probe pressure on the cold tier was booked
    assert int(new.cold.hits.sum()) > 0

    # promote: pressure past the trigger fires the daemon's promote
    # reason, and the rebuild brings the pressured rows back to device
    new.cold.hits[:] = 100
    assert mips_daemon.check_trigger(new) == "promote"
    promoted = mips.rebuild_index(table, trigger="promote")
    assert promoted is not None
    promoted_cold = (
        np.concatenate([ids for ids in promoted.cold.member_ids
                        if len(ids)])
        if promoted.cold is not None else np.empty(0, np.int64))
    assert cold_id not in promoted_cold.tolist()
    ids2 = _top_ids(table, vf[cold_id])
    assert ids2[0] == cold_id


def test_auto_tiering_waits_for_probe_samples(planted, mips_on,
                                              monkeypatch):
    """auto mode must NOT demote off an empty sample window — a
    freshly built index has all-zero counters and tiering on that
    evidence would demote the whole catalogue."""
    monkeypatch.setenv("PIO_MIPS_TIER", "auto")
    vf, _queries = planted
    table = jax.device_put(vf.copy())
    mips.build_index(table, N_ITEMS, seed=13)
    new = mips.rebuild_index(table, trigger="manual")
    assert new is not None
    assert new.cold is None


# ---------------------------------------------------------------------------
# the rebuild daemon
# ---------------------------------------------------------------------------

def test_daemon_triggers_and_sweep(planted, mips_on, monkeypatch):
    monkeypatch.setenv("PIO_MIPS_REBUILD_TAIL", "8")
    # a prior acquire/release leaves the daemon's stop flag set;
    # the synchronous sweep below must not be silenced by it
    mips_daemon.acquire()
    mips_daemon.release()
    vf, _queries = planted
    table = jax.device_put(vf.copy())
    index = mips.build_index(table, N_ITEMS, seed=13)
    assert mips_daemon.check_trigger(index) is None

    rng = np.random.default_rng(31)
    fresh = _dominating(rng, 8)
    vids = mips.publish_rows(table, fresh)
    assert mips_daemon.check_trigger(index) == "tail"

    assert mips_daemon.sweep_now() >= 1
    new = mips.index_for(table)
    assert new is not index
    assert new.tail_virtual_size() == 0
    for i, vid in enumerate(vids):
        assert _top_ids(table, fresh[i])[0] == int(vid)
    st = mips_daemon.stats()
    assert st["rebuilds"] >= 1
    assert st["tailTrigger"] == 8
    rec = st["last"][-1]
    assert rec["trigger"] == "tail"
    assert rec["traceId"]                         # booked under a trace
    assert rec["ext"] >= len(vids)

    # churn outranks age; age only fires with something to fold
    monkeypatch.setenv("PIO_MIPS_REBUILD_CHURN", "4")
    new.churn_rows = 5
    assert mips_daemon.check_trigger(new) == "churn"
    new.churn_rows = 0
    monkeypatch.setattr(mips, "_now",
                        lambda: new.built_at + 100_000.0)
    assert mips_daemon.check_trigger(new) is None  # quiet: no rebuild
    new.churn_rows = 1
    assert mips_daemon.check_trigger(new) == "age"


def test_daemon_lifecycle_is_refcounted():
    assert not mips_daemon.running()
    mips_daemon.acquire()
    mips_daemon.acquire()
    try:
        assert mips_daemon.running()
        mips_daemon.release()
        assert mips_daemon.running()              # one holder left
    finally:
        mips_daemon.release()
    assert not mips_daemon.running()
    assert mips_daemon.stats()["running"] is False


# ---------------------------------------------------------------------------
# exhaustive-fallback visibility of published rows
# ---------------------------------------------------------------------------

def test_fallback_routes_see_published_rows(planted, monkeypatch):
    monkeypatch.setenv("PIO_SERVE_MIPS", "on")
    vf, _queries = planted
    table = jax.device_put(vf.copy())
    mips.build_index(table, N_ITEMS, seed=13)
    rng = np.random.default_rng(37)
    fresh = _dominating(rng, 1)[0]
    (vid,) = mips.publish_rows(table, fresh[None, :])

    # a big exclusion list falls back to exhaustive — the published
    # key must still surface (and an excluded published key must not)
    big_ex = jnp.asarray(np.arange(1024, dtype=np.int32))
    assert mips.route(table, k=K, exclude=big_ex) is None
    ids = _top_ids(table, fresh, exclude=big_ex)
    assert ids[0] == int(vid)
    ex_vid = jnp.asarray(np.concatenate(
        [np.arange(1024), [int(vid)]]).astype(np.int32))
    assert int(vid) not in _top_ids(table, fresh, exclude=ex_vid)

    # serving mode off: the single-vector, user-row and batch wrappers
    # all merge the tail into their exhaustive results
    monkeypatch.setenv("PIO_SERVE_MIPS", "off")
    assert _top_ids(table, fresh)[0] == int(vid)
    uf = jax.device_put(np.stack([fresh, fresh]))
    packed = np.asarray(topk.score_user_and_top_k(uf, table, 1, k=K))
    assert int(packed[1][0]) == int(vid)
    batch = np.asarray(topk.batch_score_top_k(uf, table,
                                              np.asarray([0, 1]), k=K))
    assert int(batch[1][0][0]) == int(vid)
    assert int(batch[1][1][0]) == int(vid)

    # masked queries are the documented exception: a virtual id cannot
    # honor an item mask, so the mask wins and the tail stays out
    mask = jnp.asarray(np.ones(N_ITEMS, bool))
    assert int(vid) not in _top_ids(table, fresh, allowed_mask=mask)
