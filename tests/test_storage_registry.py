"""Storage registry env parsing (parity: Storage.scala:117-407)."""

import pytest

from incubator_predictionio_tpu.data.storage import (
    App,
    Storage,
    StorageError,
)


@pytest.fixture(autouse=True)
def reset_storage():
    yield
    Storage.reset()


MEM_ENV = {
    "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
    "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "pio_meta",
    "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
    "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "pio_event",
    "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
    "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "pio_model",
    "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
}


def test_env_driven_memory_backend():
    Storage.configure(MEM_ENV)
    assert Storage.verify_all_data_objects()
    apps = Storage.get_meta_data_apps()
    app_id = apps.insert(App(0, "a1"))
    # same source yields same underlying client
    assert Storage.get_meta_data_apps().get(app_id).name == "a1"


def test_split_sources():
    env = dict(MEM_ENV)
    env["PIO_STORAGE_SOURCES_MEM2_TYPE"] = "memory"
    env["PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE"] = "MEM2"
    Storage.configure(env)
    assert Storage.verify_all_data_objects()


def test_unknown_backend_type():
    Storage.configure({
        **MEM_ENV, "PIO_STORAGE_SOURCES_MEM_TYPE": "hbase",
    })
    with pytest.raises(StorageError):
        Storage.get_meta_data_apps()


def test_missing_type():
    Storage.configure({
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "NOPE",
        "PIO_STORAGE_SOURCES_NOPE_PATH": ":memory:",
    })
    with pytest.raises(StorageError):
        Storage.get_meta_data_apps()


def test_default_zero_config(tmp_path, monkeypatch):
    monkeypatch.setenv("PIO_HOME", str(tmp_path))
    Storage.configure({})
    assert Storage.verify_all_data_objects()
    assert (tmp_path / "store" / "pio.db").exists()


def test_event_store_facade():
    Storage.configure(MEM_ENV)
    from incubator_predictionio_tpu.data.event import Event
    from incubator_predictionio_tpu.data.store import EventStore, EventStoreError

    apps = Storage.get_meta_data_apps()
    apps.insert(App(0, "facade-app"))
    EventStore.write(
        [Event(event="rate", entity_type="user", entity_id="u1",
               target_entity_type="item", target_entity_id="i1")],
        app_name="facade-app",
    )
    got = list(EventStore.find(app_name="facade-app", event_names=["rate"]))
    assert len(got) == 1
    with pytest.raises(EventStoreError):
        list(EventStore.find(app_name="no-such-app"))
    with pytest.raises(EventStoreError):
        list(EventStore.find(app_name="facade-app", channel_name="nope"))


def test_partial_repository_config_errors():
    Storage.configure({
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",  # NAME missing
    })
    with pytest.raises(StorageError, match="BOTH"):
        Storage.get_meta_data_apps()
