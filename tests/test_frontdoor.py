"""Fleet front door: the failure state machine on FakeClock, the drain
choreography over real sockets, and the ephemeral-bind contract.

The unit layer drives ``FrontDoor.handle_query`` / ``_probe_pass``
directly with a scripted transport (no sockets, FakeClock time), so
every circuit/retry/shed decision is pinned deterministically:

- a worker killed mid-flight retries ONCE to a healthy peer,
- the circuit opens after N consecutive transport failures and a
  half-open probe re-admits (with cooldown doubling on probe failure),
- a shedding (503) worker is NOT ejected — shed ≠ unhealthy,
- placement follows reported queue depth.

The integration layer runs a real front door over real in-process
HttpServers: rolling reload drains with ZERO dropped queries while
client threads hammer, and priority/trace headers survive the hop.
"""

import asyncio
import json
import threading
import time
import urllib.request

import pytest

import fleet_worker
from incubator_predictionio_tpu.serving.frontdoor import (
    DRAINING,
    HALF_OPEN,
    HEALTHY,
    OPEN,
    FrontDoor,
    FrontDoorConfig,
)
from incubator_predictionio_tpu.utils.http import (
    HttpServer,
    Request,
    Response,
    Router,
)
from incubator_predictionio_tpu.utils.times import FakeClock

# -- scripted-transport unit layer ------------------------------------------

_STATUS_BODY = json.dumps({
    "status": "alive",
    "scheduler": {"engines": {"default": {"depth": 0}}}}).encode()


def make_fd(n_workers: int, clock, script, **cfg_kw):
    """FrontDoor with a scripted transport.

    ``script(worker, method, path, headers)`` returns
    ``(status, headers, body)`` or raises — exactly the real
    ``_roundtrip`` contract, minus the sockets."""
    cfg = FrontDoorConfig(**cfg_kw)
    fd = FrontDoor([("127.0.0.1", 10000 + i) for i in range(n_workers)],
                   cfg, clock=clock)

    async def roundtrip(w, method, path, headers, body, timeout):
        return script(w, method, path, headers)

    fd._roundtrip = roundtrip
    return fd


def query(fd, headers=None) -> Response:
    req = Request("POST", "/queries.json", {}, headers or {}, b"{}")
    return asyncio.run(fd.handle_query(req))


def test_midflight_kill_retries_once_to_healthy_peer():
    clock = FakeClock()
    seen = []

    def script(w, method, path, headers):
        seen.append((w.name, path))
        if w.name == "w0":
            raise ConnectionResetError("worker died mid-flight")
        return 200, {"x-pio-queue-depth": "1"}, b'{"who": "w1"}'

    # w0 wins the first pick (equal load, lower sequence)
    fd = make_fd(2, clock, script)
    resp = query(fd)
    assert resp.status == 200 and resp.body == b'{"who": "w1"}'
    assert fd.counts["retries"] == 1 and fd.counts["ok"] == 1
    assert [s for s in seen if s[1] == "/queries.json"] == [
        ("w0", "/queries.json"), ("w1", "/queries.json")]
    w0 = fd._worker("w0")
    assert w0.fails == 1 and w0.state == HEALTHY  # 1 < eject_failures


def test_no_retry_when_no_healthy_peer_exists():
    clock = FakeClock()

    def script(w, method, path, headers):
        raise ConnectionResetError("down")

    fd = make_fd(1, clock, script)
    resp = query(fd)
    assert resp.status == 502
    assert fd.counts["retries"] == 0 and fd.counts["failed"] == 1


def test_circuit_opens_after_n_failures_and_half_open_readmits():
    clock = FakeClock()
    probe_answer = {"ok": False}

    def script(w, method, path, headers):
        if method == "GET":
            if not probe_answer["ok"]:
                raise ConnectionRefusedError("still down")
            return 200, {}, _STATUS_BODY
        raise ConnectionResetError("down")

    fd = make_fd(1, clock, script, eject_failures=3, open_cooldown_s=2.0)
    w = fd._worker("w0")
    for _ in range(3):
        assert query(fd).status == 502
    assert w.state == OPEN and w.cooldown_s == 2.0
    # ejected: placement refuses, the shed contract answers
    resp = query(fd)
    assert resp.status == 503 and resp.headers["Retry-After"]
    # cooldown not elapsed: the probe pass leaves the circuit open
    clock.advance(1.0)
    asyncio.run(fd._probe_pass())
    assert w.state == OPEN
    # elapsed, but the half-open probe fails → re-open, cooldown doubles
    clock.advance(1.5)
    asyncio.run(fd._probe_pass())
    assert w.state == OPEN and w.cooldown_s == 4.0
    # next half-open probe succeeds → re-admitted, counters reset
    probe_answer["ok"] = True
    clock.advance(4.5)
    asyncio.run(fd._probe_pass())
    assert w.state == HEALTHY and w.fails == 0 and w.cooldown_s == 0.0


def test_shedding_worker_is_not_ejected():
    clock = FakeClock()

    def script(w, method, path, headers):
        return 503, {"retry-after": "2", "x-pio-queue-depth": "7"}, \
            b'{"message": "Serving overloaded"}'

    fd = make_fd(1, clock, script, eject_failures=3)
    for _ in range(5):  # way past eject_failures: shed is NOT a failure
        resp = query(fd)
        assert resp.status == 503
        assert resp.headers["Retry-After"] == "2"  # contract passthrough
    w = fd._worker("w0")
    assert w.state == HEALTHY and w.fails == 0
    assert fd.counts["shed"] == 5 and fd.counts["retries"] == 0
    assert w.depth == 7.0  # piggybacked depth was learned anyway


def test_placement_follows_reported_queue_depth():
    clock = FakeClock()

    def script(w, method, path, headers):
        return 200, {}, b"{}"

    fd = make_fd(3, clock, script)
    fd._worker("w0").depth = 5.0
    fd._worker("w2").depth = 2.0
    assert fd._pick().name == "w1"          # depth 0 wins
    fd._worker("w1").in_flight = 9          # front-door in-flight counts
    assert fd._pick().name == "w2"
    # draining and open workers never take placements
    fd._worker("w2").state = DRAINING
    fd._worker("w0").state = OPEN
    assert fd._pick().name == "w1"


def test_retry_budget_bounds_amplification():
    clock = FakeClock()

    def script(w, method, path, headers):
        raise ConnectionResetError("down")

    # budget of 1 token and no refill income: exactly one retry total
    fd = make_fd(2, clock, script, eject_failures=100, retry_budget=1.0)
    assert query(fd).status == 502
    assert query(fd).status == 502
    assert fd.counts["retries"] == 1  # second request found no budget


def test_rolling_reload_skips_rather_than_darkening_the_fleet():
    """With no healthy PEER to carry traffic, the rolling reload skips
    the worker (reported in `failed`, still serving the old model)
    instead of draining the fleet dark."""
    sent = []

    def script(w, method, path, headers):
        sent.append((w.name, method, path))
        return 200, {}, _STATUS_BODY

    # clock=None → real monotonic: the capacity wait must actually
    # expire (FakeClock would spin the wait loop forever)
    fd = make_fd(2, None, script, drain_capacity_wait_s=0.2)
    fd._worker("w1").state = OPEN
    out = asyncio.run(fd.rolling_reload_async())
    assert out["reloaded"] == 0 and out["failed"] == ["w0", "w1"]
    assert fd._worker("w0").state == HEALTHY  # never went dark
    assert ("w0", "POST", "/reload") not in sent


def test_importing_serving_package_registers_no_frontdoor_metrics():
    """The lazy re-export contract: a plain prediction worker (which
    imports serving.scheduler) must not grow empty pio_frontdoor_*
    series on its /metrics — the families register only when the
    frontdoor module itself is imported."""
    import subprocess
    import sys

    code = (
        "import incubator_predictionio_tpu.serving as s\n"
        "from incubator_predictionio_tpu.obs.metrics import REGISTRY\n"
        "assert 'pio_frontdoor' not in REGISTRY.expose()\n"
        "assert s.FrontDoorConfig().eject_failures == 3\n"  # lazy path
        "assert 'pio_frontdoor' in REGISTRY.expose()\n")
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr[-2000:]


# -- chaos hook grammar (tests/fleet_worker.py) -----------------------------

def test_chaos_spec_grammar():
    c = fleet_worker._parse_chaos(
        "kill-after=5, latency-spike=50:0.25,refuse-after=9")
    assert c["kill_after_s"] == 5.0
    assert c["latency_ms"] == 50.0 and c["latency_prob"] == 0.25
    assert c["refuse_after_s"] == 9.0 and c["stall_after_s"] is None
    assert fleet_worker._parse_chaos("")["kill_after_s"] is None
    with pytest.raises(ValueError):
        fleet_worker._parse_chaos("explode=1")


def test_chaos_latency_spike_wrapper_injects():
    calls = []

    class Rng:
        def random(self):
            return 0.0  # always below prob → always spikes

    wrapped = fleet_worker._chaos_wrap(
        lambda bodies, engine, tenant:
            calls.append(bodies) or ["ok"] * len(bodies),
        {"stall_after_s": None, "latency_ms": 5.0, "latency_prob": 0.5},
        Rng(), lambda: 0.0)
    t0 = time.perf_counter()
    assert wrapped([b"{}"], "default", "default") == ["ok"]
    assert time.perf_counter() - t0 >= 0.005
    assert calls == [[b"{}"]]


# -- real-socket integration layer ------------------------------------------

def _fake_worker(tag: str, serve_delay_s: float = 0.0):
    """An in-process stand-in for a prediction worker: /queries.json
    echoes the headers it saw, /reload records and succeeds, GET /
    answers the status shape the prober parses."""
    r = Router()
    state = {"reloads": 0, "served": 0}

    @r.post("/queries.json")
    def q(req: Request) -> Response:
        if serve_delay_s:
            time.sleep(serve_delay_s)
        state["served"] += 1
        return Response(200, {
            "who": tag,
            "sawPriority": req.headers.get("x-pio-priority"),
            "sawTrace": req.headers.get("x-pio-trace-id"),
        }, headers={"X-PIO-Queue-Depth": "0"})

    @r.get("/")
    def status(req: Request) -> Response:
        return Response(200, {"status": "alive", "scheduler": {
            "engines": {"default": {"depth": 0}}}})

    @r.post("/reload")
    def reload_route(req: Request) -> Response:
        time.sleep(0.05)  # a warm-before-swap takes real time
        state["reloads"] += 1
        return Response(200, {"message": "Reloaded."})

    srv = HttpServer(r, "127.0.0.1", 0, name=f"fake-{tag}")
    port = srv.start_background()
    return srv, port, state


@pytest.fixture
def fleet():
    servers = []

    def build(n: int, serve_delay_s: float = 0.0):
        for i in range(n):
            servers.append(_fake_worker(f"t{i}", serve_delay_s))
        fd = FrontDoor([("127.0.0.1", p) for _s, p, _st in servers],
                       FrontDoorConfig(probe_interval_s=0.2))
        servers.append((fd.http, None, None))  # stopped via fd.stop()
        fd.start_background()
        return fd, servers[:-1]

    yield build
    for srv, _p, _st in servers:
        srv.stop()


def test_priority_and_trace_headers_survive_the_hop(fleet):
    fd, workers = fleet(1)
    req = urllib.request.Request(
        f"http://127.0.0.1:{fd.http.port}/queries.json", data=b"{}",
        headers={"X-PIO-Priority": "7", "X-PIO-Trace-Id": "trace-pin"})
    with urllib.request.urlopen(req, timeout=10) as resp:
        out = json.load(resp)
        echoed = resp.headers.get("X-PIO-Trace-Id")
    assert out["sawPriority"] == "7"
    assert out["sawTrace"] == "trace-pin"  # worker joined the trace
    assert echoed == "trace-pin"           # and the client got it back


def test_rolling_reload_drains_with_zero_dropped_queries(fleet):
    fd, workers = fleet(2, serve_delay_s=0.01)
    port = fd.http.port
    statuses: list = []
    stop = threading.Event()

    def client() -> None:
        while not stop.is_set():
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/queries.json", data=b"{}")
            with urllib.request.urlopen(req, timeout=10) as resp:
                statuses.append(resp.status)

    threads = [threading.Thread(target=client) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        time.sleep(0.1)  # traffic established before the swap begins
        out = fd.rolling_reload(timeout=60)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
    # the reload swept the WHOLE fleet, dropped nothing, and every
    # query that ran concurrently succeeded
    assert out["reloaded"] == 2 and out["dropped"] == 0
    assert not out["failed"] and len(out["drainS"]) == 2
    assert all(st["reloads"] == 1 for _s, _p, st in workers)
    assert statuses and all(s == 200 for s in statuses)
    # the fleet is fully re-admitted
    assert all(w["state"] == HEALTHY for w in fd.stats()["workers"])


def test_real_kill_fails_over_and_circuit_recovers(fleet):
    fd, workers = fleet(2)
    port = fd.http.port

    def ask() -> int:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/queries.json", data=b"{}")
        with urllib.request.urlopen(req, timeout=10) as resp:
            json.load(resp)
            return resp.status

    assert ask() == 200
    workers[0][0].stop()  # hard-kill one worker's listener
    time.sleep(0.2)
    # every query still answers 200 (retry path), and the dead worker's
    # circuit opens from passive failures / probes
    for _ in range(8):
        assert ask() == 200
    deadline = time.time() + 10
    while time.time() < deadline:
        states = {w["name"]: w["state"] for w in fd.stats()["workers"]}
        if OPEN in states.values() or HALF_OPEN in states.values():
            break
        time.sleep(0.05)
    assert OPEN in states.values() or HALF_OPEN in states.values()
    assert fd.counts["failed"] == 0  # nothing leaked a 5xx to a client


# -- ephemeral bind (the spawn-path contract) -------------------------------

def test_ephemeral_bind_reports_kernel_assigned_port():
    """port=0 must bind and REPORT the kernel's choice — the fleet
    worker and front-door spawn paths key on this instead of racing
    other processes for a pre-picked 'free' port."""
    r = Router()
    a = HttpServer(r, "127.0.0.1", 0)
    b = HttpServer(r, "127.0.0.1", 0)
    pa, pb = a.start_background(), b.start_background()
    try:
        assert pa != 0 and pb != 0 and pa != pb
        assert a.port == pa and b.port == pb
    finally:
        a.stop()
        b.stop()


def test_bind_retries_remain_the_fallback_for_fixed_ports():
    """bind_retries still rescues a FIXED port whose holder is on the
    way out (the MasterActor 3×/1 s parity) — the fallback when an
    operator pins ports instead of using ephemeral bind."""
    r = Router()
    holder = HttpServer(r, "127.0.0.1", 0)
    port = holder.start_background()
    contender = HttpServer(r, "127.0.0.1", port,
                           bind_retries=10, bind_retry_delay=0.2)
    threading.Timer(0.3, holder.stop).start()
    try:
        assert contender.start_background() == port
    finally:
        holder.stop()
        contender.stop()
