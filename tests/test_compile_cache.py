"""Persistent-compile-cache wiring (utils/compile_cache.py).

The cache itself is jax's; what this framework owns — and what round-3
shipped broken — is the wiring: on platforms that pre-import jax at
interpreter startup (the TPU image's site customization), env vars are
read too late, so enable() must apply jax.config.update directly.
"""

import os

import jax
import pytest

from incubator_predictionio_tpu.utils import compile_cache


@pytest.fixture(autouse=True)
def _reset_enable_state(monkeypatch):
    monkeypatch.setattr(compile_cache, "_enabled", False)
    monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR", raising=False)
    monkeypatch.delenv("PIO_COMPILE_CACHE", raising=False)
    old = jax.config.jax_compilation_cache_dir
    yield
    jax.config.update("jax_compilation_cache_dir", old)


def test_enable_applies_config_when_jax_preimported(tmp_path):
    # jax IS imported in this process — the env-var path alone would be a
    # silent no-op, which is exactly the round-3 bug
    compile_cache.enable(str(tmp_path / "cache"))
    assert jax.config.jax_compilation_cache_dir == str(tmp_path / "cache")
    assert os.path.isdir(tmp_path / "cache")
    assert os.environ["JAX_COMPILATION_CACHE_DIR"] == str(tmp_path / "cache")


def test_enable_off_switch(tmp_path, monkeypatch):
    monkeypatch.setenv("PIO_COMPILE_CACHE", "off")
    before = jax.config.jax_compilation_cache_dir
    compile_cache.enable(str(tmp_path / "cache"))
    assert jax.config.jax_compilation_cache_dir == before
    assert not (tmp_path / "cache").exists()


def test_enable_respects_user_env_over_implicit_default(
        tmp_path, monkeypatch):
    user_dir = str(tmp_path / "user")
    monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", user_dir)
    monkeypatch.setenv("PIO_HOME", str(tmp_path / "home"))
    compile_cache.enable()  # implicit PIO_HOME default must NOT override
    assert os.environ["JAX_COMPILATION_CACHE_DIR"] == user_dir
    assert jax.config.jax_compilation_cache_dir == user_dir


def test_enable_idempotent_but_explicit_dir_repoints(tmp_path):
    compile_cache.enable(str(tmp_path / "a"))
    compile_cache.enable()  # argument-less second call: no-op
    assert jax.config.jax_compilation_cache_dir == str(tmp_path / "a")
    # an explicit dir re-points even when already enabled (the bench
    # directs different measurement phases at fresh dirs)
    compile_cache.enable(str(tmp_path / "b"))
    assert jax.config.jax_compilation_cache_dir == str(tmp_path / "b")


def test_persistent_cache_round_trip(tmp_path):
    """A compiled program lands in the cache dir and is read back after
    the in-memory executable cache is cleared (the cross-process story,
    driven in-process via jax.clear_caches)."""
    import numpy as np

    compile_cache.enable(str(tmp_path / "cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    f = jax.jit(lambda a: a * 2 + 1)
    np.asarray(f(jax.numpy.ones(16)))
    entries = list((tmp_path / "cache").iterdir())
    assert entries, "no persistent cache entry written"
    jax.clear_caches()
    np.asarray(f(jax.numpy.ones(16)))  # served from the persistent entry
