"""The driver-bench contract, end-to-end at a tiny shape.

`bench.py` is the round's external perf contract: the driver runs it
once per round and records exactly what it prints. Round 4 was lost to
this path breaking operationally (rc=3, parsed=null), so the whole
orchestrator — host stages, supervised child, kernel selector, fragment
assembly, the one-line JSON output — is pinned here on the CPU backend
at a shape small enough for CI. Every field the judge's comparisons
read must be present and typed; `degraded` must be False when the
child lands (on CPU it always can).
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")

REQUIRED_FIELDS = {
    "metric": str,
    "value": float,
    "unit": str,
    "vs_baseline": float,
    "degraded": bool,
    "train_rmse": float,
    "heldout_rmse": float,
    "seed_wall_s": float,
    "ingest_wall_s": float,
    "prep_wall_s": float,
    "ingest_http_eps": float,
    "ingest_http_eps_cap500": float,
    "movielens_rmse": float,
    "serve_p50_ms": float,
    "serve_qps_concurrent": float,
    "als_kernel": str,
    "flash_kernel_active": bool,
    "sasrec_epoch_s": float,
    "accel_waited_s": float,
    "accel_outcome": str,
    # steady-state retrain leg (docs/performance.md "Steady-state
    # retrain"): the O(delta) continuation contract's record keys
    "retrain_fresh_wall_s": float,
    "retrain_continue_wall_s": float,
    "retrain_sweeps_used": int,
    "retrain_delta_rows": int,
    "retrain_heldout_rmse_fresh": float,
    "retrain_heldout_rmse_continue": float,
    "retrain_speedup": float,
    # one-dispatch continuation retrain (fused Gram+solve PR): splice +
    # sweeps + early-stop measured as a single device dispatch
    "retrain_one_dispatch": bool,
    "retrain_train_dispatches": int,
    # speed-layer leg (docs/production.md "Freshness between retrains"):
    # device fold-in under concurrent ingest + serve
    "speed_foldin_p50_ms": float,
    "speed_foldin_p95_ms": float,
    "speed_hit_rate": float,
    "speed_cursor_lag_events": int,
    # deep-observability keys (docs/observability.md): measured
    # end-to-end freshness and the live device-time MFU attribution
    "obs_freshness_p95_s": float,
    "obs_mfu_train": float,
    # per-op pio_device_seconds cross-check over the timed warm train
    "obs_device_train_s": float,
    "obs_device_train_dispatches": int,
    # warm train wall via the fused kernel path; None on backends where
    # the selector kept the XLA assembly (the CPU CI mesh)
    "train_fused_wall_s": (float, type(None)),
    # mesh-sharded training leg (docs/performance.md "Sharded ALS"):
    # runs on the forced-8-virtual-device CPU sim in its own subprocess.
    # None is the leg's DESIGNED degraded outcome (bench deadline too
    # close, or the child subprocess failed — bench_shard nulls the
    # shard_* keys, never the record), mirroring train_fused_wall_s.
    "shard_train_wall_s": (float, type(None)),
    "shard_mesh_shape": (str, type(None)),
    "shard_devices": (int, type(None)),
    "shard_nnz": (int, type(None)),
    "shard_sweeps": (int, type(None)),
    # serving-fleet leg (docs/production.md "Serving fleet"): the
    # continuous-batching scheduler measured across real worker
    # processes. None = the leg's designed deadline-skip (same contract
    # as the shard_* keys)
    "fleet_workers": (int, type(None)),
    "fleet_qps": (float, type(None)),
    "fleet_qps_per_worker": (float, type(None)),
    "fleet_p99_s": (float, type(None)),
    "fleet_batch_p50": (float, type(None)),
    "fleet_shed_rate": (float, type(None)),
    "fleet_p99_flat_x": (float, type(None)),
    "fleet_recompiles_steady": (int, type(None)),
    # flight-recorder leg (docs/observability.md "Flight recorder &
    # incidents"): serving p99 with recorder+exemplars on vs off, and
    # the over-saturation breach's autonomous validated bundle. None =
    # the stage's designed deadline-skip.
    "recorder_overhead_p99_x": (float, type(None)),
    "fleet_incident_captured": (bool, type(None)),
    # fleet front-door leg (docs/production.md "Fleet front door"):
    # the health-checked router under injected chaos — a worker killed
    # AND a worker added mid-ramp AND a rolling fleet reload
    # mid-traffic. None = the leg's designed deadline-skip.
    "frontdoor_workers": (int, type(None)),
    "frontdoor_qps": (float, type(None)),
    "frontdoor_p99_flat_x": (float, type(None)),
    "frontdoor_nonshed_5xx": (int, type(None)),
    "frontdoor_shed_total": (int, type(None)),
    "frontdoor_retries": (int, type(None)),
    "frontdoor_reloaded": (int, type(None)),
    "frontdoor_drain_dropped": (int, type(None)),
    "frontdoor_join_cold_s": (float, type(None)),
    "frontdoor_join_warm_s": (float, type(None)),
    "frontdoor_join_to_first_dispatch_s": (float, type(None)),
    # multi-tenant noisy-neighbor leg (docs/production.md "Multi-tenant
    # platform"): two co-resident tenants on a real 2-worker fleet —
    # the aggressor floods past its admission quota and sheds ITS OWN
    # traffic while the victim's p99 stays inside its solo envelope,
    # and a tenant-scoped rolling reload of the aggressor mid-traffic
    # leaves the victim untouched. None = the leg's designed
    # deadline-skip.
    "tenant_workers": (int, type(None)),
    "tenant_victim_solo_p99_s": (float, type(None)),
    "tenant_victim_flood_p99_s": (float, type(None)),
    "tenant_victim_p99_x": (float, type(None)),
    "tenant_victim_shed_rate": (float, type(None)),
    "tenant_aggressor_shed_total": (int, type(None)),
    "tenant_aggressor_shed_rate": (float, type(None)),
    "tenant_isolation": (bool, type(None)),
    "tenant_reload_nonshed_5xx": (int, type(None)),
    "tenant_reloaded": (int, type(None)),
    # self-driving freshness leg (docs/production.md "Self-driving
    # freshness"): the SLO-burn controller alone holds fleet staleness
    # under the compressed bound — zero human retrains — with every
    # action trace-linked to its rolling-reload spans. None = the
    # leg's designed deadline-skip.
    "controller_workers": (int, type(None)),
    "controller_staleness_bound_s": (float, type(None)),
    "controller_staleness_max_s": (float, type(None)),
    "controller_staleness_held": (bool, type(None)),
    "controller_actions": (int, type(None)),
    "controller_decision_to_fresh_s": (float, type(None)),
    "controller_false_triggers": (int, type(None)),
    "controller_trace_linked": (bool, type(None)),
    "controller_evaluations": (int, type(None)),
    # self-tuning serving leg (docs/production.md "Self-tuning
    # serving"): the knob controller hill-climbs the MIPS effort back
    # to the recall target under a planted catalogue-growth ramp, lifts
    # the batch ladder under a traffic-mix flip without reversing any
    # committed direction, and a planted breach inside the newest
    # step's cooldown fires exactly one audited rollback whose incident
    # bundle froze the knob decision ring. None = the leg's designed
    # deadline-skip.
    "knob_workers": (int, type(None)),
    "knob_evaluations": (int, type(None)),
    "knob_steps": (int, type(None)),
    "knob_converged": (bool, type(None)),
    "knob_recall_final": (float, type(None)),
    "knob_false_adjustments": (int, type(None)),
    "knob_rollbacks": (int, type(None)),
    "knob_incident_ring": (bool, type(None)),
    "knob_trace_linked": (bool, type(None)),
    # planet-scale ingest leg (docs/production.md "Planet-scale
    # ingest"): multi-writer sharded append vs single-writer in the
    # same run, follower replication lag under sustained writes, and
    # the front-door soak with a rolling zero-downtime writer reload.
    # None = the leg's designed deadline-skip.
    "ingest_qps_single": (float, type(None)),
    "ingest_qps_sharded": (float, type(None)),
    "ingest_shards": (int, type(None)),
    "ingest_host_cpus": (int, type(None)),
    "ingest_replication_lag_p99_events": (int, type(None)),
    "ingest_soak_dropped_events": (int, type(None)),
    "ingest_soak_staleness_held": (bool, type(None)),
    # two-stage MIPS serving leg (docs/performance.md "Two-stage MIPS
    # serving"): exhaustive-vs-two-stage per-query walls, candidates-
    # scanned fraction and the recall@20 gate at the planted large
    # catalogue. None = the leg's designed deadline-skip.
    "mips_items": (int, type(None)),
    "mips_build_s": (float, type(None)),
    "mips_exhaustive_per_query_ms": (float, type(None)),
    "mips_two_stage_per_query_ms": (float, type(None)),
    "mips_speedup": (float, type(None)),
    "mips_candidates_frac": (float, type(None)),
    "mips_recall_at_20": (float, type(None)),
    "mips_recompiles_steady": (int, type(None)),
    "mips_serve_qps": (float, type(None)),
    "mips_exhaustive_27k_p99_ms": (float, type(None)),
    "mips_sweep": (dict, type(None)),
    # ≥10M-item MIPS lifecycle leg (docs/performance.md "Catalogue at
    # tens of millions"): the PQ recall gate at catalogue scale, the
    # flat-p99-through-rebuild ratio, the worst index age across the
    # planted churn cycle and the device bytes-per-item sizing key.
    # None = the leg's designed budget-skip (the default cost model
    # always skips on the 1-core CI box).
    "mips_big_items": (int, type(None)),
    "mips_big_build_s": (float, type(None)),
    "mips_big_recall_at_20": (float, type(None)),
    "mips_big_two_stage_p50_ms": (float, type(None)),
    "mips_rebuild_p99_flat_x": (float, type(None)),
    "mips_index_age_max_s": (float, type(None)),
    "mips_device_bytes_per_item": (float, type(None)),
    # provenance (obs/capacity.py): every record explains its origin,
    # and a record whose child landed carries no skip reason
    "bench_env": dict,
    "skipped_reason": type(None),
    "shard_allgather_bytes": (int, type(None)),
    "shard_mfu_train": (float, type(None)),
    "shard_gather_modes": (str, type(None)),
    "shard_fused_user_sweep": (bool, type(None)),
    "shard_fused_item_sweep": (bool, type(None)),
    "shard_fused_fits_ml20m_user_sweep": (bool, type(None)),
    "shard_fused_fits_ml20m_item_sweep": (bool, type(None)),
}


def test_bench_emits_one_parsed_record_end_to_end(tmp_path):
    # hermetic movielens sample (the default path lives outside the
    # repo): same user::item::rating format, enough rows for the 80/20
    # split to produce a real number
    import numpy as np
    rng = np.random.default_rng(0)
    sample = tmp_path / "movielens.txt"
    sample.write_text("".join(
        f"{rng.integers(1, 40)}::{rng.integers(1, 25)}::"
        f"{rng.integers(1, 6)}\n" for _ in range(500)))
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PIO_BENCH_NNZ": "30000",
        "PIO_BENCH_RANK": "16",
        "PIO_BENCH_SWEEPS": "2",
        "PIO_BENCH_ATTN_SEQS": "512",
        "PIO_BENCH_ATTN_REPS": "2",
        "PIO_BENCH_DEGRADED_NNZ": "20000",
        "PIO_BENCH_INGEST_CLIENTS": "8",
        "PIO_BENCH_INGEST_BATCHES": "20",
        "PIO_BENCH_MOVIELENS": str(sample),
        "PIO_BENCH_MOVIELENS_BOUND": "10.0",  # synthetic data, shape only
        # MIPS leg at CI shape: the 256k gate size runs, the 1M rung is
        # left to real bench rounds (CI wall budget)
        "PIO_BENCH_MIPS_ITEMS": "27000,262144",
        "PIO_BENCH_MIPS_QUERIES": "24",
        # front-door chaos leg at CI shape: shorter stages, same chaos
        # choreography (kill + join + rolling reload all still fire)
        "PIO_BENCH_FRONTDOOR_STAGE_S": "5",
        "PIO_BENCH_FRONTDOOR_RAMP_RPS": "80,80,80",
        # controller leg at CI shape: tighter staleness bound + shorter
        # ramp — the full trigger→retrain→rolling-swap choreography
        # still fires at least once
        "PIO_BENCH_CONTROLLER_BOUND_S": "6",
        "PIO_BENCH_CONTROLLER_RUN_S": "18",
        "PIO_BENCH_CONTROLLER_RPS": "25",
    })
    # own session so a timeout kill reaps the whole tree — otherwise the
    # claimed child outlives the parent and keeps burning CPU
    proc = subprocess.Popen(
        [sys.executable, BENCH], env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True, cwd=str(tmp_path),
        start_new_session=True)
    try:
        stdout, stderr = proc.communicate(timeout=540)
    except subprocess.TimeoutExpired:
        import signal
        os.killpg(proc.pid, signal.SIGKILL)  # CPU-only tree: safe
        proc.wait()
        raise
    assert proc.returncode == 0, stderr[-2000:]
    # contract: exactly one JSON line on stdout
    lines = [ln for ln in stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, stdout
    rec = json.loads(lines[0])
    for field, typ in REQUIRED_FIELDS.items():
        assert field in rec, f"missing {field}"
        assert isinstance(rec[field], typ), (field, rec[field])
    assert rec["degraded"] is False          # the CPU child always lands
    assert rec["value"] > 0
    assert rec["ingest_http_eps"] > 0
    # telemetry cross-check keys (docs/observability.md): the registry
    # snapshot corroborates the bench's own measurements — the ingest
    # counter saw at least the cap-50 HTTP load, and the child's query
    # histogram saw the serving stage
    assert rec["obs_ingest_events_total"] >= 8 * 20 * 50
    assert rec["obs_ingest_batches"] >= 8 * 20
    assert rec["obs_query_latency_count"] > 0
    assert rec["obs_query_p50_ms"] > 0
    # the selector on a Mosaic-less backend reports honestly
    assert rec["als_kernel"] in ("unavailable", "disabled", "on", "off",
                                 "probe_failed")
    # retrain leg sanity: the continuation actually stopped early or at
    # worst used the full budget, and the delta matches the 5% tail
    assert 1 <= rec["retrain_sweeps_used"] <= rec["sweeps"]
    assert rec["retrain_delta_rows"] >= 1
    assert rec["retrain_continue_wall_s"] > 0
    assert rec["retrain_fresh_wall_s"] > 0
    # speed leg sanity: cold users were ingested AND folded in, the
    # overlay served hits, and the fold-in cycle produced real walls
    assert rec["speed_foldins"] >= 1
    assert rec["speed_ingested_keys"] >= 1
    assert 0.0 < rec["speed_hit_rate"] <= 1.0
    assert rec["speed_foldin_p50_ms"] > 0
    assert rec["speed_foldin_p95_ms"] >= rec["speed_foldin_p50_ms"]
    assert rec["speed_cursor_lag_events"] >= 0
    # end-to-end freshness came from the new pio_freshness_seconds
    # histogram (event append -> first folded serve): a real, positive
    # figure — the speed layer's promise, measured rather than inferred
    assert rec["obs_freshness_p95_s"] > 0
    # the live pio_mfu{phase=train} gauge and the bench's offline MFU
    # divide the SAME analytic FLOPs by near-identical walls — they must
    # agree within 10% or one of them lies (the ratio is computed in
    # the child against the UNROUNDED offline figure; the record's
    # "mfu" itself is 4-decimal-rounded and reads 0.0 on CPU backends)
    assert rec["obs_mfu_train"] > 0
    assert 0.90 <= rec["obs_mfu_vs_offline"] <= 1.10, (
        rec["obs_mfu_train"], rec["obs_mfu_vs_offline"], rec["mfu"])
    # per-op device-seconds cross-check: the profiler's block-until-ready
    # wall over the SAME timed warm run must bracket the bench's own
    # wall (generous band — CI boxes are noisy), and the whole training
    # run must have been ONE attributed dispatch
    assert rec["obs_device_train_s"] > 0
    assert 0.5 <= rec["obs_device_train_s"] / rec["value"] <= 1.5, (
        rec["obs_device_train_s"], rec["value"])
    assert rec["obs_device_train_dispatches"] == 1
    # one-dispatch continuation retrain: the timed continue leg ran
    # splice + sweeps + early-stop as a single device dispatch
    assert rec["retrain_one_dispatch"] is True, (
        rec["retrain_train_dispatches"])
    assert rec["retrain_train_dispatches"] == 1
    # mesh-sharded leg: the placed train ran over all 8 forced host
    # devices, moved real collective bytes, and the ML-20M VMEM math
    # shows the fused kernel routes on BOTH half-sweeps once sharded
    # (per-shard slice residency — the ROADMAP item 1/5 unlock). A None
    # here means the leg's designed degraded outcome fired (deadline too
    # close on a loaded box) — the record stays valid, the pins apply
    # whenever the leg actually ran.
    # bench_env provenance block: the trajectory's "what produced this
    # row" answer (backend/devices from the process that measured)
    env_block = rec["bench_env"]
    for key in ("backend", "device_count", "jax_version", "git_sha",
                "hostname", "wall_ts", "python"):
        assert key in env_block, key
    assert env_block["backend"] == "cpu"
    assert env_block["device_count"] >= 1
    # serving-fleet leg: queue-depth-adaptive batching demonstrably
    # engaged (the fused width's p50 under peak offered load beats the
    # old fixed max_batch=64), p99 stayed flat (≤1.5×) across the
    # offered-load ramp, and the peak stage compiled NOTHING new (the
    # zero-steady-state-recompile contract, fleet edition). None =
    # the leg's designed deadline-skip.
    if rec["fleet_workers"] is not None:
        # every key individually null-guarded: fleet_workers is set
        # before the load runs, so a stage that produced no serves
        # leaves later keys None — that must read as a clear assertion,
        # not a NoneType comparison TypeError
        assert rec["fleet_workers"] >= 2
        assert rec["fleet_qps"] is not None and rec["fleet_qps"] > 0
        assert rec["fleet_qps_per_worker"] is not None \
            and rec["fleet_qps_per_worker"] > 0
        assert rec["fleet_p99_s"] is not None \
            and rec["fleet_p99_s"] > 0, rec["fleet_p99_s"]
        assert rec["fleet_batch_p50"] is not None \
            and rec["fleet_batch_p50"] > 64, rec["fleet_batch_p50"]
        assert rec["fleet_p99_flat_x"] is not None \
            and rec["fleet_p99_flat_x"] <= 1.5, rec["fleet_p99_flat_x"]
        assert rec["fleet_recompiles_steady"] == 0
        assert rec["fleet_shed_rate"] is not None \
            and 0.0 <= rec["fleet_shed_rate"] <= 1.0
        # flight recorder: always-on history + exemplars must not move
        # serving p99 (the ≤1.1× overhead pin), and the planted
        # over-saturation breach must have frozen ONE bundle that
        # passes incident_report --check — autonomously, worker-side
        if rec["recorder_overhead_p99_x"] is not None:
            assert rec["recorder_overhead_p99_x"] <= 1.1, \
                rec["recorder_overhead_p99_x"]
        if rec["fleet_incident_captured"] is not None:
            assert rec["fleet_incident_captured"] is True
    # fleet front-door leg: when the leg ran, its two hard bars hold
    # under the injected chaos — every 5xx a client saw carried the
    # 503 + Retry-After shed contract (kills were retried to healthy
    # peers, never leaked), and the rolling reload dropped nothing.
    # The p99-flatness and join-speed figures are recorded for the
    # capacity trajectory but asserted only on real bench rounds (a
    # loaded CI box can blur sub-100ms tails).
    if rec["frontdoor_workers"] is not None:
        assert rec["frontdoor_workers"] >= 2
        if rec["frontdoor_nonshed_5xx"] is not None:
            assert rec["frontdoor_nonshed_5xx"] == 0
        if rec["frontdoor_drain_dropped"] is not None:
            assert rec["frontdoor_drain_dropped"] == 0
        if rec["frontdoor_join_to_first_dispatch_s"] is not None:
            assert rec["frontdoor_join_to_first_dispatch_s"] > 0
        if rec["frontdoor_join_cold_s"] is not None:
            assert rec["frontdoor_join_cold_s"] > 0
    # multi-tenant noisy-neighbor leg: when the leg ran, isolation held
    # end to end — the victim's flooded p99 stayed inside 1.5× its own
    # solo baseline, the victim shed NOTHING (the aggressor's quota
    # displaced only aggressor traffic, per the workers' own per-tenant
    # /status evidence), and the tenant-scoped rolling reload of the
    # aggressor's deploy produced zero non-shed 5xx on the victim.
    if rec["tenant_workers"] is not None:
        assert rec["tenant_workers"] >= 2
        if rec["tenant_victim_p99_x"] is not None:
            assert rec["tenant_victim_p99_x"] <= 1.5, \
                rec["tenant_victim_p99_x"]
        if rec["tenant_victim_shed_rate"] is not None:
            assert rec["tenant_victim_shed_rate"] == 0, \
                rec["tenant_victim_shed_rate"]
        if rec["tenant_isolation"] is not None:
            assert rec["tenant_isolation"] is True, \
                (rec["tenant_aggressor_shed_total"],
                 rec["tenant_victim_shed_rate"])
        if rec["tenant_reload_nonshed_5xx"] is not None:
            assert rec["tenant_reload_nonshed_5xx"] == 0
        if rec["tenant_reloaded"] is not None:
            assert rec["tenant_reloaded"] >= 1
    # self-driving freshness leg: when the leg ran, the controller —
    # acting alone, zero human retrains — kept the sampled fleet-max
    # staleness under the compressed bound, fired at least one
    # retrain+swap, fired NO false triggers (the hysteresis/horizon
    # promise), and every action's decision trace ID reached the
    # rolling-reload hop (the audit-trail acceptance bar).
    if rec["controller_workers"] is not None:
        assert rec["controller_workers"] >= 2
        assert rec["controller_actions"] is not None \
            and rec["controller_actions"] >= 1, rec["controller_actions"]
        if rec["controller_staleness_held"] is not None:
            assert rec["controller_staleness_held"] is True, \
                rec["controller_staleness_max_s"]
        if rec["controller_false_triggers"] is not None:
            assert rec["controller_false_triggers"] == 0
        if rec["controller_trace_linked"] is not None:
            assert rec["controller_trace_linked"] is True
        if rec["controller_decision_to_fresh_s"] is not None:
            assert rec["controller_decision_to_fresh_s"] > 0
    # self-tuning serving leg: when the leg ran, the knob controller
    # converged the planted recall sag back over the target (the
    # hill-climb promise), never reversed a committed direction (the
    # hysteresis/cooldown promise), rolled back EXACTLY once on the
    # planted breach with the knob ring frozen into the incident
    # bundle, and every actuated decision's trace reached the front
    # door's /knobs hop (the audit-trail acceptance bar).
    if rec["knob_workers"] is not None:
        assert rec["knob_workers"] >= 2
        assert rec["knob_steps"] is not None \
            and rec["knob_steps"] >= 1, rec["knob_steps"]
        if rec["knob_converged"] is not None:
            assert rec["knob_converged"] is True, \
                rec["knob_recall_final"]
        if rec["knob_false_adjustments"] is not None:
            assert rec["knob_false_adjustments"] == 0
        if rec["knob_trace_linked"] is not None:
            assert rec["knob_trace_linked"] is True
        if rec["knob_rollbacks"] is not None:
            assert rec["knob_rollbacks"] == 1, rec["knob_rollbacks"]
        if rec["knob_incident_ring"] is not None:
            assert rec["knob_incident_ring"] is True
    # planet-scale ingest leg: when the leg ran, the sharded append is
    # a real measurement (both qps keys positive, shard count > 1), the
    # soak dropped ZERO events across the rolling writer reload and
    # held the staleness bound, and the follower caught the leader. The
    # sharded-vs-single ratio is a PARALLELISM bar: the fan-out
    # overlaps per-shard native appends on distinct cores, so it is
    # asserted only when the recording host had at least one core per
    # writer shard (a 1-core CI box has no parallel headroom by
    # construction — the record still carries both figures).
    if rec["ingest_qps_single"] is not None:
        assert rec["ingest_qps_single"] > 0
        assert rec["ingest_qps_sharded"] is not None \
            and rec["ingest_qps_sharded"] > 0
        assert rec["ingest_shards"] is not None \
            and rec["ingest_shards"] >= 2
        assert rec["ingest_host_cpus"] is not None \
            and rec["ingest_host_cpus"] >= 1
        if rec["ingest_host_cpus"] >= rec["ingest_shards"]:
            assert rec["ingest_qps_sharded"] \
                >= 2.0 * rec["ingest_qps_single"], (
                rec["ingest_qps_sharded"], rec["ingest_qps_single"])
        if rec["ingest_soak_dropped_events"] is not None:
            assert rec["ingest_soak_dropped_events"] == 0
        if rec["ingest_soak_staleness_held"] is not None:
            assert rec["ingest_soak_staleness_held"] is True
        if rec["ingest_replication_lag_p99_events"] is not None:
            assert rec["ingest_replication_lag_p99_events"] >= 0
    # two-stage MIPS leg: at the ≥128k planted gate size the two-stage
    # path must beat exhaustive per query while scanning ≤ 25% of the
    # catalogue at recall@20 ≥ 0.95, with ZERO steady-state recompiles;
    # the exhaustive path itself stays measured (the 27k p99 key) so
    # the capacity trajectory can pin it. None = designed deadline-skip.
    if rec["mips_items"] is not None:
        assert rec["mips_items"] >= 131072
        assert rec["mips_recall_at_20"] is not None \
            and rec["mips_recall_at_20"] >= 0.95, rec["mips_recall_at_20"]
        assert rec["mips_candidates_frac"] is not None \
            and rec["mips_candidates_frac"] <= 0.25, \
            rec["mips_candidates_frac"]
        assert rec["mips_two_stage_per_query_ms"] is not None \
            and rec["mips_exhaustive_per_query_ms"] is not None \
            and rec["mips_two_stage_per_query_ms"] \
            < rec["mips_exhaustive_per_query_ms"], (
                rec["mips_two_stage_per_query_ms"],
                rec["mips_exhaustive_per_query_ms"])
        assert rec["mips_recompiles_steady"] == 0
        assert rec["mips_serve_qps"] is not None \
            and rec["mips_serve_qps"] > 0
        assert rec["mips_exhaustive_27k_p99_ms"] is not None \
            and rec["mips_exhaustive_27k_p99_ms"] > 0
        assert rec["mips_sweep"], rec["mips_sweep"]
    # catalogue-at-scale leg: when it ran, the PQ recall gate holds at
    # ≥10M items at well under f32 bytes/item, serving p99 through the
    # background rebuild-and-swap stays ≤1.5× the quiet baseline, and
    # the index never ages past the planted churn cycle's ceiling.
    # None = designed budget-skip (the 1-core box never pays for it).
    if rec["mips_big_items"] is not None:
        assert rec["mips_big_items"] >= 1_000_000
        assert rec["mips_big_recall_at_20"] is not None \
            and rec["mips_big_recall_at_20"] >= 0.95, \
            rec["mips_big_recall_at_20"]
        assert rec["mips_rebuild_p99_flat_x"] is not None \
            and rec["mips_rebuild_p99_flat_x"] <= 1.5, \
            rec["mips_rebuild_p99_flat_x"]
        assert rec["mips_index_age_max_s"] is not None \
            and rec["mips_index_age_max_s"] <= 600.0, \
            rec["mips_index_age_max_s"]
        assert rec["mips_device_bytes_per_item"] is not None \
            and rec["mips_device_bytes_per_item"] > 0
    if rec["shard_devices"] is not None:
        assert rec["shard_devices"] == 8
        assert rec["shard_mesh_shape"] == "8x1"
        assert rec["shard_nnz"] > 0 and rec["shard_sweeps"] >= 1
        assert rec["shard_train_wall_s"] > 0
        assert rec["shard_allgather_bytes"] > 0
        assert rec["shard_mfu_train"] > 0
        assert rec["shard_fused_fits_ml20m_user_sweep"] is True
        assert rec["shard_fused_fits_ml20m_item_sweep"] is True
