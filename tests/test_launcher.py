"""Pod launcher (parallel/launcher.py) — the Runner.runOnSpark role.

The heavyweight proof: PodLauncher actually brings up a 2-process pod on
localhost whose workers join one jax.distributed runtime and run a
numerics-checked ALS sweep (tests/distributed_worker.py — the same worker
the raw 2-process test uses, now spawned and supervised by the launcher).
"""

import os
import subprocess
import sys
from pathlib import Path

import pytest

from incubator_predictionio_tpu.parallel.launcher import PodLauncher


def _base_env():
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    repo_root = str(Path(__file__).parent.parent)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (repo_root, env.get("PYTHONPATH")) if p)
    return env


def test_launcher_runs_two_process_pod():
    worker = str(Path(__file__).parent / "distributed_worker.py")
    lines = []
    launcher = PodLauncher(
        ["local", "localhost"], [sys.executable, worker],
        env_extra=_base_env(),
    )
    # env_extra must reach the workers; the trio is set per process
    assert launcher._worker_env(1)["PIO_PROCESS_ID"] == "1"
    assert launcher._worker_env(1)["PIO_NUM_PROCESSES"] == "2"
    rc = launcher.launch(sink=lines.append, timeout=240)
    joined = "\n".join(lines)
    assert rc == 0, joined
    # both workers streamed through the supervisor with host tags
    assert any(line.startswith("[0:local]") for line in lines), joined
    assert any(line.startswith("[1:localhost]") for line in lines), joined
    assert joined.count("WORKER_OK") == 2, joined


def test_launcher_tears_down_pod_on_first_failure():
    ok = [sys.executable, "-c",
          "import time, os\n"
          "time.sleep(0 if os.environ['PIO_PROCESS_ID']=='0' else 120)\n"
          "raise SystemExit(3 if os.environ['PIO_PROCESS_ID']=='0' else 0)"]
    launcher = PodLauncher(["local", "local"], ok, env_extra=_base_env())
    rc = launcher.launch(sink=lambda _l: None, timeout=60)
    assert rc != 0
    # the healthy-but-sleeping worker was terminated, not waited out
    assert all(p.poll() is not None for p in launcher.procs)


def test_ssh_command_construction():
    launcher = PodLauncher(
        ["tpu-host-a", "tpu-host-b"], ["pio", "train"],
        coordinator_port=5555,
    )
    assert launcher.coordinator == "tpu-host-a:5555"
    cmd_env = launcher._worker_env(1)
    assert cmd_env["PIO_COORDINATOR_ADDRESS"] == "tpu-host-a:5555"
    assert cmd_env["PIO_NUM_PROCESSES"] == "2"
    # remote spawn goes through ssh with env on the command line
    captured = {}

    def fake_popen(cmd, **kw):
        captured["cmd"] = cmd
        raise RuntimeError("stop here")

    import incubator_predictionio_tpu.parallel.launcher as mod
    orig = mod.subprocess.Popen
    mod.subprocess.Popen = fake_popen
    try:
        with pytest.raises(RuntimeError):
            launcher._spawn("user@tpu-host-b", 1)
    finally:
        mod.subprocess.Popen = orig
    cmd = captured["cmd"]
    assert cmd[:3] == ["ssh", "-o", "BatchMode=yes"]
    assert "user@tpu-host-b" in cmd
    assert any(a.startswith("PIO_PROCESS_ID=") for a in cmd)
    assert cmd[-2:] == ["pio", "train"]


def test_relaunch_strips_hosts_flag(monkeypatch):
    import incubator_predictionio_tpu.parallel.launcher as mod

    seen = {}

    class FakeLauncher:
        def __init__(self, hosts, argv, **kw):
            seen["hosts"] = hosts
            seen["argv"] = argv

        def launch(self):
            return 0

    monkeypatch.setattr(mod, "PodLauncher", FakeLauncher)
    monkeypatch.setattr(
        mod.sys, "argv",
        ["pio", "train", "--hosts", "a,b", "--variant", "engine.json"])
    assert mod.relaunch_over_hosts(["a", "b"]) == 0
    assert seen["hosts"] == ["a", "b"]
    assert "--hosts" not in seen["argv"] and "a,b" not in seen["argv"]
    assert seen["argv"][-2:] == ["--variant", "engine.json"]


def test_cli_worker_joins_runtime_when_coordinator_set():
    """`pio train` inside a launched worker must call
    jax.distributed.initialize before engine code runs — proven by a
    1-process pod whose worker reports process_count from inside the CLI
    path (eval of a trivial command avoids needing an engine dir)."""
    code = (
        "import os\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "import jax\n"
        # sitecustomize may pin the config to a real-TPU platform; the
        # config update re-selects CPU before backends initialize
        # (tests/conftest.py does the same)
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from incubator_predictionio_tpu.parallel.distributed import "
        "ensure_initialized\n"
        "ensure_initialized()\n"
        "print('COUNT', jax.process_count())\n"
    )
    # use the launcher itself for a 1-process pod: trio set, port picked
    launcher = PodLauncher(["local"], [sys.executable, "-c", code],
                           env_extra=_base_env())
    lines = []
    rc = launcher.launch(sink=lines.append, timeout=120)
    assert rc == 0, "\n".join(lines)
    assert any("COUNT 1" in line for line in lines)


def test_killed_worker_fails_cleanly_no_corrupt_instance(tmp_path):
    """The supervision half of Runner.scala:101-213: a pod worker dying
    mid-train (SIGKILL — a crash, not a polite exit) must produce a clean
    nonzero supervisor failure with the surviving worker torn down and NO
    corrupt EngineInstance — the store may hold an ABORTED record or
    nothing, but never COMPLETED and never a model blob."""
    import json
    import signal
    import sqlite3

    engine_dir = tmp_path / "engine"
    engine_dir.mkdir()
    (engine_dir / "crashengine.py").write_text(
        "import os, signal\n"
        "import numpy as np\n"
        "import jax.numpy as jnp\n"
        "from incubator_predictionio_tpu.core import (\n"
        "    Algorithm, DataSource, Engine, EngineFactory, FirstServing,\n"
        "    IdentityPreparator)\n"
        "\n"
        "class DS(DataSource):\n"
        "    def read_training(self, ctx):\n"
        "        return np.arange(32, dtype=np.float32)\n"
        "\n"
        "class Algo(Algorithm):\n"
        "    def train(self, ctx, td):\n"
        "        if os.environ.get('PIO_PROCESS_ID') == '1':\n"
        "            os.kill(os.getpid(), signal.SIGKILL)  # worker crash\n"
        "        return float(jnp.mean(jnp.asarray(td)))\n"
        "    def predict(self, model, query):\n"
        "        return model\n"
        "\n"
        "class CrashEngine(EngineFactory):\n"
        "    def apply(self):\n"
        "        return Engine(DS, IdentityPreparator, {'a': Algo},\n"
        "                      FirstServing)\n"
    )
    (engine_dir / "engine.json").write_text(json.dumps({
        "id": "crash-test",
        "engineFactory": "crashengine:CrashEngine",
    }))
    env = _base_env()
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PIO_HOME": str(tmp_path / "home"),
        "PIO_STORAGE_SOURCES_SQL_TYPE": "sqlite",
        "PIO_STORAGE_SOURCES_SQL_PATH": str(tmp_path / "pio.db"),
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "SQL",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "SQL",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "SQL",
    })
    proc = subprocess.run(
        [sys.executable, "-m", "incubator_predictionio_tpu.cli.main",
         "train", "--hosts", "local,localhost"],
        cwd=engine_dir, env=env, capture_output=True, text=True,
        timeout=420,
    )
    out = proc.stdout + proc.stderr
    assert proc.returncode != 0, out

    db = tmp_path / "pio.db"
    if db.exists():
        conn = sqlite3.connect(str(db))
        try:
            statuses = [r[0] for r in conn.execute(
                "SELECT status FROM engine_instances").fetchall()]
        except sqlite3.OperationalError:
            statuses = []  # table never created — also clean
        assert "COMPLETED" not in statuses, statuses
        try:
            (n_models,) = conn.execute(
                "SELECT COUNT(*) FROM models").fetchone()
        except sqlite3.OperationalError:
            n_models = 0
        assert n_models == 0, n_models
        conn.close()
