"""Storage conformance suite: one shared behavior spec × N backends.

Mirrors the reference's pattern of running the identical spec against every
backend (data/src/test/.../storage/LEventsSpec.scala:24-52, PEventsSpec.scala).
"""

from datetime import timedelta

import pytest

from incubator_predictionio_tpu.data.datamap import DataMap
from incubator_predictionio_tpu.data.event import Event
from incubator_predictionio_tpu.data.storage import (
    AccessKey,
    App,
    Channel,
    EngineInstance,
    EvaluationInstance,
    Model,
    StorageClientConfig,
    UNSET,
)
from incubator_predictionio_tpu.data.storage import memory as memory_backend
from incubator_predictionio_tpu.data.storage import sqlite as sqlite_backend
from incubator_predictionio_tpu.utils.times import now_utc, parse_iso8601

T0 = parse_iso8601("2021-06-01T00:00:00Z")


@pytest.fixture(params=["memory", "sqlite", "cpplog", "remote"])
def backend(request, tmp_path):
    if request.param == "cpplog":
        # the native event-log backend (events only); skip its spec slice
        # when the toolchain can't build the library
        from incubator_predictionio_tpu import native
        if native.load() is None:
            pytest.skip("native library unavailable")
        from incubator_predictionio_tpu.data.storage import (
            cpplog as cpplog_backend,
        )
        config = StorageClientConfig(
            test=True, properties={"PATH": str(tmp_path / "cpplog")})
        mod = cpplog_backend
    elif request.param == "remote":
        # the network backend: the SAME spec runs through a real
        # StorageServer over HTTP (loopback), backed by the memory backend —
        # the multi-box topology the reference gets from PostgreSQL/HBase
        from incubator_predictionio_tpu.data.storage import (
            remote as remote_backend,
        )
        from incubator_predictionio_tpu.data.storage.server import (
            StorageServer,
        )

        back_config = StorageClientConfig(test=True, properties={})
        back_client = memory_backend.StorageClient(back_config)
        srv = StorageServer(memory_backend, back_client, back_config,
                            host="127.0.0.1", port=0)
        port = srv.start_background()
        config = StorageClientConfig(
            test=True, properties={"URL": f"http://127.0.0.1:{port}"})
        client = remote_backend.StorageClient(config)
        yield remote_backend, client, config
        client.close()
        srv.stop()
        return
    else:
        config = StorageClientConfig(
            test=True, properties={"PATH": ":memory:"})
        mod = {"memory": memory_backend,
               "sqlite": sqlite_backend}[request.param]
    client = mod.StorageClient(config)
    yield mod, client, config
    client.close()


def dao(backend, iface):
    mod, client, config = backend
    if iface not in mod.DATA_OBJECTS:
        pytest.skip(f"{mod.__name__} does not implement {iface}")
    return mod.DATA_OBJECTS[iface](client, config, prefix="test_")


def ev(name="rate", eid="u1", minutes=0, target=None, props=None):
    return Event(
        event=name,
        entity_type="user",
        entity_id=eid,
        target_entity_type="item" if target else None,
        target_entity_id=target,
        properties=DataMap(props or {}),
        event_time=T0 + timedelta(minutes=minutes),
    )


# ---------------------------------------------------------------------------
# Events
# ---------------------------------------------------------------------------

def test_event_crud(backend):
    events = dao(backend, "Events")
    events.init(1)
    e = ev(target="i1", props={"rating": 5})
    eid = events.insert(e, 1)
    got = events.get(eid, 1)
    assert got is not None
    assert got.event_id == eid
    assert got.entity_id == "u1"
    assert got.target_entity_id == "i1"
    assert got.properties.get("rating") in (5, 5.0)
    assert got.event_time == e.event_time
    assert events.delete(eid, 1)
    assert events.get(eid, 1) is None
    assert not events.delete(eid, 1)


def test_event_channel_isolation(backend):
    events = dao(backend, "Events")
    events.init(1)
    events.init(1, 7)
    eid = events.insert(ev(), 1, 7)
    assert events.get(eid, 1) is None
    assert events.get(eid, 1, 7) is not None
    assert list(events.find(app_id=1)) == []
    assert len(list(events.find(app_id=1, channel_id=7))) == 1


def test_event_app_isolation_and_remove(backend):
    events = dao(backend, "Events")
    events.init(1)
    events.init(2)
    events.insert(ev(), 1)
    events.insert(ev(), 2)
    events.remove(1)
    assert list(events.find(app_id=1)) == []
    assert len(list(events.find(app_id=2))) == 1


def test_find_filters(backend):
    events = dao(backend, "Events")
    events.init(1)
    events.insert(ev("rate", "u1", 0, target="i1"), 1)
    events.insert(ev("buy", "u1", 10, target="i2"), 1)
    events.insert(ev("rate", "u2", 20, target="i1"), 1)
    events.insert(ev("$set", "u3", 30, props={"a": 1}), 1)

    assert len(list(events.find(app_id=1))) == 4
    assert len(list(events.find(app_id=1, event_names=["rate"]))) == 2
    assert len(list(events.find(app_id=1, entity_id="u1"))) == 2
    assert len(list(events.find(app_id=1, entity_type="user"))) == 4
    # time range: start inclusive, until exclusive
    got = list(
        events.find(
            app_id=1,
            start_time=T0 + timedelta(minutes=10),
            until_time=T0 + timedelta(minutes=30),
        )
    )
    assert [e.event for e in got] == ["buy", "rate"]
    # target entity filtering incl. explicit None
    assert len(list(events.find(app_id=1, target_entity_id="i1"))) == 2
    assert len(list(events.find(app_id=1, target_entity_type=None))) == 1
    assert len(list(events.find(app_id=1, target_entity_type="item"))) == 3


def test_find_order_limit_reversed(backend):
    events = dao(backend, "Events")
    events.init(1)
    for m in (5, 0, 10):
        events.insert(ev("rate", "u1", m), 1)
    asc = [e.event_time for e in events.find(app_id=1)]
    assert asc == sorted(asc)
    desc = [e.event_time for e in events.find(app_id=1, reversed=True)]
    assert desc == sorted(desc, reverse=True)
    limited = list(events.find(app_id=1, limit=2))
    assert len(limited) == 2
    assert list(events.find(app_id=1, limit=-1)) and len(list(events.find(app_id=1, limit=-1))) == 3


def test_aggregate_properties_via_dao(backend):
    events = dao(backend, "Events")
    events.init(1)
    events.insert(ev("$set", "u1", 0, props={"a": 1, "b": 2}), 1)
    events.insert(ev("$unset", "u1", 1, props={"b": None}), 1)
    events.insert(ev("$set", "u2", 0, props={"a": 9}), 1)
    events.insert(ev("$delete", "u2", 1), 1)
    events.insert(ev("rate", "u1", 2, target="i1"), 1)
    out = events.aggregate_properties(app_id=1, entity_type="user")
    assert set(out) == {"u1"}
    assert out["u1"].fields == {"a": 1}
    out2 = events.aggregate_properties(app_id=1, entity_type="user", required=["zz"])
    assert out2 == {}


# ---------------------------------------------------------------------------
# Metadata
# ---------------------------------------------------------------------------

def test_apps(backend):
    apps = dao(backend, "Apps")
    app_id = apps.insert(App(0, "myapp", "desc"))
    assert app_id
    assert apps.get(app_id).name == "myapp"
    assert apps.get_by_name("myapp").id == app_id
    assert apps.insert(App(0, "myapp")) is None  # duplicate name
    assert apps.update(App(app_id, "renamed", None))
    assert apps.get_by_name("renamed") is not None
    assert len(apps.get_all()) == 1
    assert apps.delete(app_id)
    assert apps.get(app_id) is None


def test_access_keys(backend):
    keys = dao(backend, "AccessKeys")
    k = keys.insert(AccessKey("", 1, ("rate", "buy")))
    assert k and len(k) >= 32
    assert keys.get(k).events == ("rate", "buy")
    k2 = keys.insert(AccessKey("explicit-key", 1))
    assert k2 == "explicit-key"
    assert len(keys.get_by_appid(1)) == 2
    assert keys.get_by_appid(2) == []
    assert keys.update(AccessKey(k, 1, ()))
    assert keys.get(k).events == ()
    assert keys.delete(k)
    assert keys.get(k) is None


def test_channels(backend):
    channels = dao(backend, "Channels")
    cid = channels.insert(Channel(0, "chan-1", 1))
    assert cid
    assert channels.get(cid).name == "chan-1"
    assert channels.insert(Channel(0, "chan-1", 1)) is None  # dup in app
    assert channels.insert(Channel(0, "chan-1", 2)) is not None  # other app ok
    assert [c.id for c in channels.get_by_appid(1)] == [cid]
    assert channels.delete(cid)
    assert channels.get(cid) is None
    with pytest.raises(ValueError):
        Channel(0, "bad name!", 1)
    with pytest.raises(ValueError):
        Channel(0, "x" * 17, 1)


def test_engine_instances(backend):
    instances = dao(backend, "EngineInstances")
    t = now_utc()

    def mk(status, start, variant="v1"):
        return EngineInstance(
            id="", status=status, start_time=start, end_time=start,
            engine_id="e", engine_version="1", engine_variant=variant,
            engine_factory="f", batch="b", env={"K": "V"},
            runtime_conf={"mesh": "2x4"}, data_source_params="dsp",
            preparator_params="pp", algorithms_params="ap", serving_params="sp",
        )

    i1 = instances.insert(mk("INIT", t))
    assert instances.get(i1).status == "INIT"
    assert instances.get(i1).env == {"K": "V"}
    i2 = instances.insert(mk("COMPLETED", t + timedelta(minutes=1)))
    i3 = instances.insert(mk("COMPLETED", t + timedelta(minutes=2)))
    instances.insert(mk("COMPLETED", t + timedelta(minutes=3), variant="other"))
    latest = instances.get_latest_completed("e", "1", "v1")
    assert latest.id == i3
    completed = instances.get_completed("e", "1", "v1")
    assert [i.id for i in completed] == [i3, i2]
    import dataclasses as dc
    assert instances.update(dc.replace(instances.get(i1), status="COMPLETED"))
    assert instances.get(i1).status == "COMPLETED"
    assert instances.delete(i1)
    assert instances.get(i1) is None
    assert len(instances.get_all()) == 3


def test_evaluation_instances(backend):
    instances = dao(backend, "EvaluationInstances")
    t = now_utc()

    def mk(status, start):
        return EvaluationInstance(
            id="", status=status, start_time=start, end_time=start,
            evaluation_class="Eval", engine_params_generator_class="Gen",
            batch="b", evaluator_results="res",
            evaluator_results_html="<p>", evaluator_results_json="{}",
        )

    i1 = instances.insert(mk("EVALUATING", t))
    i2 = instances.insert(mk("EVALCOMPLETED", t + timedelta(minutes=1)))
    i3 = instances.insert(mk("EVALCOMPLETED", t + timedelta(minutes=2)))
    assert [i.id for i in instances.get_completed()] == [i3, i2]
    assert instances.get(i1).evaluation_class == "Eval"
    assert instances.delete(i2)
    assert [i.id for i in instances.get_completed()] == [i3]


def test_models(backend):
    models = dao(backend, "Models")
    blob = b"\x00\x01binary\xff"
    models.insert(Model("m1", blob))
    assert models.get("m1").models == blob
    models.insert(Model("m1", b"new"))
    assert models.get("m1").models == b"new"
    models.delete("m1")
    assert models.get("m1") is None


def test_localfs_models(tmp_path):
    from incubator_predictionio_tpu.data.storage import localfs

    config = StorageClientConfig(properties={"PATH": str(tmp_path)})
    client = localfs.StorageClient(config)
    models = localfs.LocalFSModels(client, config, prefix="pio_")
    models.insert(Model("m1", b"blob"))
    assert (tmp_path / "pio_m1").exists()
    assert models.get("m1").models == b"blob"
    models.delete("m1")
    assert models.get("m1") is None


class TestGCSModels:
    """The gcs driver over the in-process JSON-API emulator — the real
    wire path (media upload, alt=media download, delete, 404 mapping),
    parity: hdfs/HDFSModels.scala via SURVEY.md:34's replacement table."""

    @pytest.fixture
    def emulator(self):
        from incubator_predictionio_tpu.data.storage import gcs

        srv = gcs.EmulatorServer()
        port = srv.start_background()
        yield srv, port
        srv.stop()

    def _models(self, port, prefix="pio_", base_path=""):
        from incubator_predictionio_tpu.data.storage import gcs

        config = StorageClientConfig(properties={
            "BUCKET": "models-bucket",
            "BASE_PATH": base_path,
            "EMULATOR_HOST": f"127.0.0.1:{port}",
        })
        client = gcs.StorageClient(config)
        return gcs.GCSModels(client, config, prefix=prefix), client

    def test_conformance(self, emulator):
        srv, port = emulator
        models, client = self._models(port)
        blob = b"\x00\x01binary\xff" * 100
        models.insert(Model("m1", blob))
        assert models.get("m1").models == blob
        models.insert(Model("m1", b"new"))          # overwrite = upsert
        assert models.get("m1").models == b"new"
        assert models.get("absent") is None
        models.delete("m1")
        assert models.get("m1") is None
        models.delete("m1")                          # idempotent delete
        client.close()

    def test_base_path_and_object_layout(self, emulator):
        srv, port = emulator
        models, client = self._models(port, base_path="pio/models")
        models.insert(Model("inst-1", b"x"))
        # the blob lands under the configured key space — what a pod's
        # other hosts (and gsutil) will see
        assert srv.objects["models-bucket"]["pio/models/pio_inst-1"] == b"x"
        client.close()

    def test_registry_wiring(self, emulator, monkeypatch):
        """TYPE=gcs resolves through the storage registry env shape."""
        from incubator_predictionio_tpu.data.storage import Storage

        _, port = emulator
        Storage.reset()
        Storage.configure({
            "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
            "PIO_STORAGE_SOURCES_GCS_TYPE": "gcs",
            "PIO_STORAGE_SOURCES_GCS_BUCKET": "models-bucket",
            "PIO_STORAGE_SOURCES_GCS_EMULATOR_HOST": f"127.0.0.1:{port}",
            "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "pio_model_",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "GCS",
        })
        try:
            models = Storage.get_model_data_models()
            models.insert(Model("wired", b"ok"))
            assert models.get("wired").models == b"ok"
        finally:
            Storage.reset()


# ---------------------------------------------------------------------------
# Review-fix regressions
# ---------------------------------------------------------------------------

def test_auto_id_skips_explicit_ids(backend):
    apps = dao(backend, "Apps")
    assert apps.insert(App(1, "explicit")) == 1
    auto = apps.insert(App(0, "auto"))
    assert auto is not None and auto != 1
    channels = dao(backend, "Channels")
    assert channels.insert(Channel(5, "chan-a", 1)) == 5
    auto_c = channels.insert(Channel(0, "chan-b", 1))
    assert auto_c is not None and auto_c != 5


def test_namespace_isolation(backend):
    mod, client, config = backend
    if "Apps" in mod.DATA_OBJECTS:
        apps_a = mod.DATA_OBJECTS["Apps"](client, config, prefix="nsA_")
        apps_b = mod.DATA_OBJECTS["Apps"](client, config, prefix="nsB_")
        assert apps_a.insert(App(0, "same-name")) is not None
        # no cross-ns clash
        assert apps_b.insert(App(0, "same-name")) is not None
        assert apps_a.get_by_name("same-name") is not None
        assert len(apps_a.get_all()) == 1
    events_a = mod.DATA_OBJECTS["Events"](client, config, prefix="nsA_")
    events_b = mod.DATA_OBJECTS["Events"](client, config, prefix="nsB_")
    events_a.insert(ev(), 1)
    assert list(events_b.find(app_id=1)) == []
    assert len(list(events_a.find(app_id=1))) == 1


def _seed_interaction_events(events):
    """A spread of training-shaped events exercising every scan rule."""
    from incubator_predictionio_tpu.data.event import Event as Ev

    events.init(9)
    rows = [
        # (event, entity_id, target, props, minutes)
        ("rate", "alice", "i1", {"rating": 4.5}, 0),
        ("rate", "bob", "i2", {"rating": 2.0}, 1),
        ("rate", "alice", "i2", {}, 2),            # missing prop → skipped
        ("rate", "carol", "i1", {"rating": "hi"}, 3),  # non-numeric → skip
        ("buy", "bob", "i3", {}, 4),               # fixed value 4.0
        ("view", "dave", "i1", {}, 5),             # name not in scan
        ("rate", "éva", "ïtem-√2", {"rating": 5.0}, 6),  # non-ascii ids
        ("rate", 'q"uote\\back', "i1", {"rating": 1.5}, 7),  # escapes
        ("rate", "alice", "i1", {"rating": 3.0}, 8),  # later re-rate
    ]
    for name, eid, target, props, minutes in rows:
        events.insert(Ev(
            event=name, entity_type="user", entity_id=eid,
            target_entity_type="item", target_entity_id=target,
            properties=DataMap(props),
            event_time=T0 + timedelta(minutes=minutes),
        ), 9)
    # wrong entity_type / wrong target type: excluded by the scan
    events.insert(Ev(
        event="rate", entity_type="item", entity_id="i1",
        target_entity_type="item", target_entity_id="i9",
        properties=DataMap({"rating": 9.0}),
        event_time=T0 + timedelta(minutes=9)), 9)
    events.insert(Ev(
        event="rate", entity_type="user", entity_id="zed",
        target_entity_type="category", target_entity_id="c1",
        properties=DataMap({"rating": 9.0}),
        event_time=T0 + timedelta(minutes=10)), 9)
    # no target entity at all
    events.insert(Ev(
        event="rate", entity_type="user", entity_id="zed",
        properties=DataMap({"rating": 9.0}),
        event_time=T0 + timedelta(minutes=11)), 9)


#: triples the scan must yield, in event-time order
_EXPECTED_TRIPLES = [
    ("alice", "i1", 4.5),
    ("bob", "i2", 2.0),
    ("bob", "i3", 4.0),
    ("éva", "ïtem-√2", 5.0),
    ('q"uote\\back', "i1", 1.5),
    ("alice", "i1", 3.0),
]


def _triples(inter):
    return [
        (inter.user_ids[int(u)], inter.item_ids[int(i)], float(v))
        for u, i, v in zip(inter.user_idx, inter.item_idx, inter.values)
    ]


def test_scan_interactions_conformance(backend):
    """Every backend's columnar scan must match the generic semantics:
    value resolution (fixed per name > value_prop > skip), filters, and
    event-time ordering of the triples."""
    events = dao(backend, "Events")
    _seed_interaction_events(events)
    inter = events.scan_interactions(
        app_id=9, entity_type="user", target_entity_type="item",
        event_names=("rate", "buy"), value_prop="rating",
        event_values={"buy": 4.0},
    )
    assert _triples(inter) == _EXPECTED_TRIPLES
    assert inter.user_idx.dtype.name == "int32"
    assert inter.values.dtype.name == "float32"
    # id tables hold exactly the referenced ids, in FIRST-SEEN
    # (event-time, insertion) order — the cross-backend contract
    assert list(inter.user_ids) == ["alice", "bob", "éva", 'q"uote\\back']
    assert list(inter.item_ids) == ["i1", "i2", "i3", "ïtem-√2"]
    # and agree with the generic (Event-object) implementation
    from incubator_predictionio_tpu.data.storage import base as storage_base
    generic = storage_base.Events.scan_interactions(
        events, app_id=9, entity_type="user", target_entity_type="item",
        event_names=("rate", "buy"), value_prop="rating",
        event_values={"buy": 4.0},
    )
    assert _triples(generic) == _EXPECTED_TRIPLES


def test_scan_interactions_time_window_and_defaults(backend):
    events = dao(backend, "Events")
    _seed_interaction_events(events)
    # window [min 1, min 7) keeps bob/i2, buy, éva
    inter = events.scan_interactions(
        app_id=9, event_names=("rate", "buy"), value_prop="rating",
        event_values={"buy": 4.0},
        start_time=T0 + timedelta(minutes=1),
        until_time=T0 + timedelta(minutes=7),
    )
    assert _triples(inter) == _EXPECTED_TRIPLES[1:4]
    # no value_prop: every non-fixed event scores default_value
    inter = events.scan_interactions(
        app_id=9, event_names=("view",), default_value=1.0)
    assert _triples(inter) == [("dave", "i1", 1.0)]
    # empty names match nothing (find() contract)
    inter = events.scan_interactions(app_id=9, event_names=())
    assert len(inter) == 0 and inter.user_ids == []


def test_scan_interactions_json_fallback_path(backend):
    """Records whose sidecar cannot be built (a numeric property key beyond
    the sidecar's 255-byte key limit) must scan identically through the
    JSON-parsing fallback (eventlog.cc extract_fields/span_property_number;
    trivially true for the non-native backends)."""
    from incubator_predictionio_tpu.data.event import Event as Ev

    events = dao(backend, "Events")
    events.init(11)
    long_key = "k" * 300  # forces sidecar_ok=False in the cpplog writer
    rows = [
        ("alice", "i1", 4.5, 0),
        ("éva", "ïtem-√2", 5.0, 1),
        ('q"uote\\back', "i1", 1.5, 2),
    ]
    for eid, target, rating, minutes in rows:
        events.insert(Ev(
            event="rate", entity_type="user", entity_id=eid,
            target_entity_type="item", target_entity_id=target,
            properties=DataMap({"rating": rating, long_key: 1.0}),
            event_time=T0 + timedelta(minutes=minutes),
        ), 11)
    # one event missing the prop → skipped by value resolution
    events.insert(Ev(
        event="rate", entity_type="user", entity_id="bob",
        target_entity_type="item", target_entity_id="i2",
        properties=DataMap({long_key: 1.0}),
        event_time=T0 + timedelta(minutes=3)), 11)
    inter = events.scan_interactions(
        app_id=11, event_names=("rate",), value_prop="rating")
    assert _triples(inter) == [(u, t, v) for u, t, v, _ in rows]
    assert list(inter.user_ids) == ["alice", "éva", 'q"uote\\back']


def test_insert_batch_duplicate_explicit_id_last_wins(backend):
    """Duplicate explicit event ids inside ONE batch resolve last-wins,
    matching sqlite INSERT OR REPLACE / upsert-across-batches semantics."""
    events = dao(backend, "Events")
    events.init(12)
    e1 = ev("rate", "u1", 0, target="i1", props={"rating": 1.0})
    batch = [
        e1.with_id("dup-id"),
        ev("rate", "u2", 1, target="i2", props={"rating": 2.0}),
        ev("rate", "u1", 2, target="i3",
           props={"rating": 3.0}).with_id("dup-id"),
    ]
    ids = events.insert_batch(batch, 12)
    assert ids == ["dup-id", ids[1], "dup-id"]
    got = events.get("dup-id", 12)
    assert got is not None and got.target_entity_id == "i3"
    # exactly two live records: the winner and the independent event
    assert len(list(events.find(app_id=12))) == 2


def test_import_interactions_roundtrip(backend):
    """Columnar bulk import (the inverse of scan_interactions) must
    round-trip exactly on every backend — incl. the fully-native cpplog
    writer (eventlog.cc pio_evlog_append_interactions)."""
    import numpy as np

    from incubator_predictionio_tpu.data.storage.base import Interactions

    events = dao(backend, "Events")
    events.init(13)
    inter = Interactions(
        user_idx=np.array([0, 1, 0, 2, 1], np.int32),
        item_idx=np.array([0, 0, 1, 2, 1], np.int32),
        values=np.array([4.5, 2.0, 3.25, 1.0, 5.0], np.float32),
        user_ids=["alice", "éva", 'q"uote\\back'],
        item_ids=["i1", "ïtem-√2", "i3"],
    )
    n = events.import_interactions(
        inter, 13, entity_type="user", target_entity_type="item",
        event_name="rate", value_prop="rating", base_time=T0)
    assert n == 5
    back = events.scan_interactions(
        app_id=13, entity_type="user", target_entity_type="item",
        event_names=("rate",), value_prop="rating")
    assert _triples(back) == [
        ("alice", "i1", 4.5), ("éva", "i1", 2.0),
        ("alice", "ïtem-√2", 3.25), ('q"uote\\back', "i3", 1.0),
        ("éva", "ïtem-√2", 5.0),
    ]
    # the imported records are real events (queryable, typed, timestamped)
    found = list(events.find(app_id=13, entity_id="alice"))
    assert len(found) == 2
    assert found[0].event == "rate"
    assert found[0].properties.get("rating") in (4.5,)
    assert found[0].event_time == T0
    assert found[0].event_id  # ids were generated


def test_aggregate_required_filters_by_property_names(backend):
    events = dao(backend, "Events")
    events.init(1)
    events.insert(ev("$set", "u1", 0, props={"rating": 5, "zip": "10001"}), 1)
    events.insert(ev("$set", "u2", 0, props={"zip": "94305"}), 1)
    out = events.aggregate_properties(app_id=1, entity_type="user",
                                      required=["rating"])
    assert set(out) == {"u1"}
    out2 = events.aggregate_properties(app_id=1, entity_type="user",
                                       required=["rating", "zip"])
    assert set(out2) == {"u1"}
    out3 = events.aggregate_properties(app_id=1, entity_type="user",
                                       required=["zip"])
    assert set(out3) == {"u1", "u2"}
