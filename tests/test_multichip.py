"""Multi-device sharding tests on the virtual 8-device CPU mesh
(the reference simulates its cluster with local[4] Spark threads,
core/src/test/.../workflow/BaseTest.scala:71-88 — same idea, real shardings).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from incubator_predictionio_tpu.ops import als_init, als_sweep, als_train
from incubator_predictionio_tpu.ops.sparse import build_padded_rows
from incubator_predictionio_tpu.parallel.mesh import MODEL_AXIS, make_mesh, mesh_shape_for
from incubator_predictionio_tpu.parallel.sharding import replicated, shard_buckets


def test_mesh_shape_factorization():
    assert mesh_shape_for(8, 1) == (8, 1)
    assert mesh_shape_for(8, 2) == (4, 2)
    assert mesh_shape_for(8, 3) == (4, 2)  # clamped to divisor
    assert mesh_shape_for(8, 16) == (1, 8)
    assert mesh_shape_for(1, 4) == (1, 1)


def test_make_mesh_uses_all_devices():
    mesh = make_mesh(model_parallelism=2)
    assert mesh.devices.size == 8
    assert mesh.shape == {"dp": 4, "mp": 2}


def test_sharded_sweep_matches_single_device():
    rng = np.random.default_rng(0)
    n_users, n_items, nnz, rank = 48, 32, 400, 8
    users = rng.integers(0, n_users, nnz)
    items = rng.integers(0, n_items, nnz)
    vals = rng.uniform(1, 5, nnz).astype(np.float32)

    # single-device reference
    ub = build_padded_rows(users, items, vals, n_users)
    ib = build_padded_rows(items, users, vals, n_items)
    state0 = als_init(jax.random.key(0), n_users, n_items, rank)
    ref = als_sweep(state0, ub, ib, l2=0.1)

    # 8-device mesh with mp=2
    mesh = make_mesh(model_parallelism=2)
    ub8 = shard_buckets(build_padded_rows(users, items, vals, n_users,
                                          row_multiple=8), mesh)
    ib8 = shard_buckets(build_padded_rows(items, users, vals, n_items,
                                          row_multiple=8), mesh)
    state8 = als_init(jax.random.key(0), n_users, n_items, rank)
    state8 = jax.tree_util.tree_map(
        lambda x: jax.device_put(x, replicated(mesh)), state8
    )
    out = als_sweep(state8, ub8, ib8, l2=0.1)

    np.testing.assert_allclose(
        np.asarray(ref.user_factors), np.asarray(out.user_factors),
        rtol=2e-4, atol=2e-5,
    )
    np.testing.assert_allclose(
        np.asarray(ref.item_factors), np.asarray(out.item_factors),
        rtol=2e-4, atol=2e-5,
    )


def test_mp_sharded_serving_matmul():
    mesh = make_mesh(model_parallelism=4)
    uf = jnp.ones((8, 16))
    item = jax.device_put(
        jnp.arange(32 * 16, dtype=jnp.float32).reshape(32, 16),
        NamedSharding(mesh, P(MODEL_AXIS)),
    )

    @jax.jit
    def serve(u, v):
        return jax.lax.top_k(u @ v.T, 3)

    scores, idx = serve(uf, item)
    assert idx.shape == (8, 3)
    assert idx[0, 0] == 31  # largest-row item wins


def test_graft_entry_contract():
    import __graft_entry__ as g

    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    shapes = [x.shape for x in jax.tree_util.tree_leaves(out)]
    assert shapes == [(8, 10), (8, 10)]
    g.dryrun_multichip(8)


class TestModelParallelTraining:
    """als_train_sharded: factor tables sharded over mp (the ALX layout)."""

    @pytest.mark.parametrize("model_parallelism", [2, 4])
    def test_matches_unsharded(self, model_parallelism):
        from incubator_predictionio_tpu.ops.als import als_train_sharded
        rng = np.random.default_rng(1)
        n_users, n_items, nnz, rank = 50, 30, 500, 8
        users = rng.integers(0, n_users, nnz)
        items = rng.integers(0, n_items, nnz)
        vals = rng.uniform(1, 5, nnz).astype(np.float32)

        ref, _ = als_train(users, items, vals, n_users, n_items, rank=rank,
                           iterations=3, seed=4)
        mesh = make_mesh(model_parallelism=model_parallelism)
        out = als_train_sharded(users, items, vals, n_users, n_items, mesh,
                                rank=rank, iterations=3, seed=4)
        np.testing.assert_allclose(
            np.asarray(ref.user_factors), np.asarray(out.user_factors),
            rtol=2e-4, atol=2e-5)
        np.testing.assert_allclose(
            np.asarray(ref.item_factors), np.asarray(out.item_factors),
            rtol=2e-4, atol=2e-5)

    def test_tables_actually_sharded_on_mp(self):
        from incubator_predictionio_tpu.ops.als import (
            ALSState, _als_run_fused, _buckets_tree, als_init,
        )
        from incubator_predictionio_tpu.parallel.sharding import (
            batch_sharding, model_sharding,
        )
        rng = np.random.default_rng(2)
        n_users, n_items, rank = 64, 32, 8
        users = rng.integers(0, n_users, 300)
        items = rng.integers(0, n_items, 300)
        vals = rng.uniform(1, 5, 300).astype(np.float32)
        mesh = make_mesh(model_parallelism=4)
        ub = build_padded_rows(users, items, vals, n_users, row_multiple=8)
        ib = build_padded_rows(items, users, vals, n_items, row_multiple=8)
        tables = model_sharding(mesh)
        rows = batch_sharding(mesh)
        st = als_init(jax.random.key(0), n_users, n_items, rank)
        st = ALSState(jax.device_put(st.user_factors, tables),
                      jax.device_put(st.item_factors, tables))

        def place(tree):
            return tuple(tuple(jax.device_put(a, rows) for a in b)
                         for b in tree)

        out = _als_run_fused(
            st, place(_buckets_tree(ub)), place(_buckets_tree(ib)),
            0.1, 0.0, 2, True, jnp.float32, jax.lax.Precision.HIGHEST,
            implicit=False)
        # the result keeps the mp row sharding (no silent full replication)
        spec = out.user_factors.sharding.spec
        assert spec[0] == MODEL_AXIS, spec

    def test_split_rows_on_mesh(self):
        from incubator_predictionio_tpu.ops.als import als_train_sharded
        rng = np.random.default_rng(3)
        users = np.concatenate([np.zeros(40, np.int64),
                                rng.integers(1, 20, 200)])
        items = np.concatenate([np.arange(40) % 24,
                                rng.integers(0, 24, 200)]).astype(np.int64)
        vals = rng.uniform(1, 5, 240).astype(np.float32)
        ref, _ = als_train(users, items, vals, 20, 24, rank=8, iterations=3,
                           seed=5, max_width=16)
        mesh = make_mesh(model_parallelism=2)
        out = als_train_sharded(users, items, vals, 20, 24, mesh, rank=8,
                                iterations=3, seed=5, max_width=16)
        np.testing.assert_allclose(
            np.asarray(ref.user_factors), np.asarray(out.user_factors),
            rtol=2e-4, atol=2e-5)

    def test_implicit_on_mesh(self):
        from incubator_predictionio_tpu.ops.als import (
            als_train_implicit, als_train_sharded,
        )
        rng = np.random.default_rng(6)
        users = rng.integers(0, 30, 400)
        items = rng.integers(0, 20, 400)
        w = rng.random(400).astype(np.float32) + 0.5
        ref = als_train_implicit(users, items, w, 30, 20, rank=8,
                                 iterations=3, seed=7)
        mesh = make_mesh(model_parallelism=2)
        out = als_train_sharded(users, items, w, 30, 20, mesh, rank=8,
                                iterations=3, seed=7, implicit=True)
        np.testing.assert_allclose(
            np.asarray(ref.user_factors), np.asarray(out.user_factors),
            rtol=2e-4, atol=2e-5)
