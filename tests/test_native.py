"""Native runtime components: event-log engine + CSR builder.

The storage behavior spec runs against cpplog via test_storage_conformance;
this file covers what only the native layer has: durability across reopen
(the reference proves the same with live-service storage tests,
data/src/test/.../storage/LEventsSpec.scala), tombstone persistence, and
bit-equality of the C++ CSR builder with the numpy reference.
"""

from datetime import timedelta

import numpy as np
import pytest

from incubator_predictionio_tpu import native
from incubator_predictionio_tpu.data.datamap import DataMap
from incubator_predictionio_tpu.data.event import Event
from incubator_predictionio_tpu.data.storage import StorageClientConfig
from incubator_predictionio_tpu.ops.sparse import build_padded_rows
from incubator_predictionio_tpu.utils.times import parse_iso8601

pytestmark = pytest.mark.skipif(
    native.load() is None, reason="native library unavailable")

T0 = parse_iso8601("2021-06-01T00:00:00Z")


def _client(path):
    from incubator_predictionio_tpu.data.storage import cpplog
    return cpplog.StorageClient(
        StorageClientConfig(properties={"PATH": str(path)}))


def _events(client):
    from incubator_predictionio_tpu.data.storage import cpplog
    return cpplog.CppLogEvents(client, client.config, prefix="t_")


def ev(name="rate", eid="u1", minutes=0, target=None, props=None):
    return Event(
        event=name, entity_type="user", entity_id=eid,
        target_entity_type="item" if target else None,
        target_entity_id=target,
        properties=DataMap(props or {}),
        event_time=T0 + timedelta(minutes=minutes),
    )


class TestEventLogDurability:
    def test_events_survive_reopen(self, tmp_path):
        c1 = _client(tmp_path)
        d1 = _events(c1)
        d1.init(1)
        ids = [d1.insert(ev(minutes=i, eid=f"u{i}"), 1) for i in range(5)]
        d1.delete(ids[2], 1)
        c1.close()

        c2 = _client(tmp_path)  # fresh handle: index rebuilt from disk
        d2 = _events(c2)
        found = list(d2.find(app_id=1))
        assert [e.event_id for e in found] == [
            ids[0], ids[1], ids[3], ids[4]]  # tombstone persisted
        assert d2.get(ids[2], 1) is None
        assert d2.get(ids[3], 1).entity_id == "u3"
        c2.close()

    def test_upsert_replaces_across_reopen(self, tmp_path):
        c1 = _client(tmp_path)
        d1 = _events(c1)
        d1.init(1)
        eid = d1.insert(ev(props={"rating": 1}), 1)
        d1.insert(ev(props={"rating": 9}).with_id(eid), 1)
        assert d1.get(eid, 1).properties.get("rating") == 9
        assert len(list(d1.find(app_id=1))) == 1
        c1.close()

        c2 = _client(tmp_path)
        d2 = _events(c2)
        assert d2.get(eid, 1).properties.get("rating") == 9
        assert len(list(d2.find(app_id=1))) == 1
        c2.close()

    def test_torn_tail_truncated_on_reopen(self, tmp_path):
        """A crash mid-append leaves a record claiming payload past EOF; the
        reopen scan must drop + truncate it so later appends never start
        inside its claimed range (eventlog.cc open-scan extent check)."""
        c1 = _client(tmp_path)
        d1 = _events(c1)
        d1.init(1)
        good = [d1.insert(ev(minutes=i, eid=f"u{i}"), 1) for i in range(3)]
        c1.close()

        log_file = next(tmp_path.glob("*.log"))
        intact = log_file.stat().st_size
        # forge a torn record: full 48-byte header claiming a 500-byte
        # payload, but only 10 payload bytes made it to disk
        import struct
        with open(log_file, "ab") as f:
            f.write(struct.pack("<qQQQQIi", 12345, 2, 3, 4, 5, 500, 0))
            f.write(b"x" * 10)

        c2 = _client(tmp_path)
        d2 = _events(c2)
        found = list(d2.find(app_id=1))
        assert [e.event_id for e in found] == good
        # the torn tail was physically truncated away
        assert log_file.stat().st_size == intact
        # appends after recovery frame correctly across another reopen
        extra = d2.insert(ev(minutes=9, eid="u9"), 1)
        c2.close()
        c3 = _client(tmp_path)
        d3 = _events(c3)
        assert [e.event_id for e in d3.find(app_id=1)] == good + [extra]
        c3.close()

    def test_torn_header_truncated_on_reopen(self, tmp_path):
        c1 = _client(tmp_path)
        d1 = _events(c1)
        d1.init(1)
        good = d1.insert(ev(minutes=0, eid="u0"), 1)
        c1.close()

        log_file = next(tmp_path.glob("*.log"))
        intact = log_file.stat().st_size
        with open(log_file, "ab") as f:
            f.write(b"\x01" * 20)  # partial header

        c2 = _client(tmp_path)
        d2 = _events(c2)
        assert [e.event_id for e in d2.find(app_id=1)] == [good]
        assert log_file.stat().st_size == intact
        c2.close()

    def test_out_of_order_times_sorted_and_limited(self, tmp_path):
        c = _client(tmp_path)
        d = _events(c)
        d.init(1)
        for m in (5, 1, 9, 3, 7):
            d.insert(ev(minutes=m, eid=f"u{m}"), 1)
        asc = [e.entity_id for e in d.find(app_id=1)]
        assert asc == ["u1", "u3", "u5", "u7", "u9"]
        top2 = [e.entity_id for e in d.find(app_id=1, reversed=True, limit=2)]
        assert top2 == ["u9", "u7"]
        window = [e.entity_id for e in d.find(
            app_id=1, start_time=T0 + timedelta(minutes=3),
            until_time=T0 + timedelta(minutes=9))]
        assert window == ["u3", "u5", "u7"]
        c.close()


class TestNativeCsrBuilder:
    @pytest.mark.parametrize("seed,n_rows,n_cols,nnz,max_width", [
        (0, 50, 40, 600, 64),
        (1, 7, 5, 30, 8),      # tiny, single bucket
        (2, 100, 30, 2000, 16),  # heavy rows split at max_width
    ])
    def test_matches_numpy_reference(self, seed, n_rows, n_cols, nnz,
                                     max_width):
        rng = np.random.default_rng(seed)
        rows = rng.integers(0, n_rows, nnz).astype(np.int64)
        cols = rng.integers(0, n_cols, nnz).astype(np.int32)
        vals = rng.random(nnz).astype(np.float32)
        ref = build_padded_rows(rows, cols, vals, n_rows,
                                max_width=max_width, impl="numpy")
        got = build_padded_rows(rows, cols, vals, n_rows,
                                max_width=max_width, impl="native")
        assert len(ref) == len(got)
        for r, g in zip(ref, got):
            np.testing.assert_array_equal(r.row_ids, g.row_ids)
            np.testing.assert_array_equal(r.cols, g.cols)
            np.testing.assert_array_equal(r.vals, g.vals)
            np.testing.assert_array_equal(r.mask, g.mask)

    def test_ids_beyond_int32_fall_back_to_numpy_path(self):
        """Indices ≥ 2^31 would silently wrap in the int32 cast for C++;
        the guard must return None (→ caller uses the int64 numpy path)."""
        from incubator_predictionio_tpu.native.csr import build_buckets_native
        rows = np.array([0, 2**31 + 5], np.int64)
        cols = np.array([0, 1], np.int64)
        vals = np.array([1.0, 2.0], np.float32)
        assert build_buckets_native(
            rows, cols, vals, n_rows=2**31 + 6, min_width=8, max_width=64,
        ) is None

    def test_empty_rows_and_empty_input(self):
        # rows 3..9 have no entries; row 0 dense
        rows = np.array([0] * 10 + [2], np.int64)
        cols = np.arange(11, dtype=np.int32)
        vals = np.ones(11, np.float32)
        ref = build_padded_rows(rows, cols, vals, 10, impl="numpy")
        got = build_padded_rows(rows, cols, vals, 10, impl="native")
        assert len(ref) == len(got)
        for r, g in zip(ref, got):
            np.testing.assert_array_equal(r.cols, g.cols)
        assert build_padded_rows(
            np.empty(0, np.int64), np.empty(0, np.int32),
            np.empty(0, np.float32), 4, impl="native") == []

    def test_auto_dispatch_threshold(self, monkeypatch):
        import incubator_predictionio_tpu.ops.sparse as sparse
        monkeypatch.setattr(sparse, "NATIVE_MIN_NNZ", 10)
        rng = np.random.default_rng(3)
        rows = rng.integers(0, 20, 500).astype(np.int64)
        cols = rng.integers(0, 20, 500).astype(np.int32)
        vals = rng.random(500).astype(np.float32)
        auto = sparse.build_padded_rows(rows, cols, vals, 20)
        ref = sparse.build_padded_rows(rows, cols, vals, 20, impl="numpy")
        for a, r in zip(auto, ref):
            np.testing.assert_array_equal(a.cols, r.cols)


class TestCompactRecords:
    """Compact interaction records (kCompact): sidecar-only storage with
    JSON rendered on read — readers must not be able to tell."""

    def test_rendered_json_matches_canonical_shape(self, tmp_path):
        import json

        from incubator_predictionio_tpu.data.storage.base import (
            IdTable,
            Interactions,
        )

        client = _client(tmp_path)
        dao = _events(client)
        inter = Interactions(
            user_idx=np.array([0, 1], np.int32),
            item_idx=np.array([1, 0], np.int32),
            values=np.array([4.5, 2.0], np.float32),
            user_ids=IdTable.from_list(['u"quote', "uplain"]),
            item_ids=IdTable.from_list(["i\\back", "iplain"]),
        )
        n = dao.import_interactions(
            inter, 1, event_name="rate", value_prop="rating",
            base_time=None)
        assert n == 2
        got = sorted(dao.find(app_id=1), key=lambda e: e.entity_id)
        # rendered JSON must re-serialize losslessly through the DAO's
        # canonical json.dumps(to_jsonable) — same keys, escapes, values
        for e in got:
            doc = e.to_jsonable()
            round2 = Event.from_jsonable(
                json.loads(json.dumps(doc))).to_jsonable()
            assert round2 == doc
        assert got[0].entity_id == 'u"quote'
        assert got[0].target_entity_id == "iplain"
        assert got[1].target_entity_id == "i\\back"
        assert got[0].properties.get("rating") == 4.5
        assert got[0].event_id and len(got[0].event_id) == 32
        # compact storage really is compact: well under the JSON form
        size = sum(f.stat().st_size for f in tmp_path.iterdir())
        assert size < 2 * 250, size

    def test_compact_records_survive_reopen_and_tombstone(self, tmp_path):
        from incubator_predictionio_tpu.data.storage.base import (
            IdTable,
            Interactions,
        )

        client = _client(tmp_path)
        dao = _events(client)
        inter = Interactions(
            user_idx=np.arange(5, dtype=np.int32),
            item_idx=np.zeros(5, np.int32),
            values=np.ones(5, np.float32),
            user_ids=IdTable.from_list([f"u{k}" for k in range(5)]),
            item_ids=IdTable.from_list(["i0"]),
        )
        dao.import_interactions(inter, 1, event_name="rate",
                                value_prop="rating", base_time=None)
        first = next(iter(dao.find(app_id=1, limit=1)))
        assert dao.delete(first.event_id, 1)
        client.close()

        client2 = _client(tmp_path)
        dao2 = _events(client2)
        live = list(dao2.find(app_id=1))
        assert len(live) == 4
        assert first.event_id not in {e.event_id for e in live}
        # columnar scan over reopened compact records
        back = dao2.scan_interactions(
            app_id=1, entity_type="user", target_entity_type="item",
            event_names=("rate",), value_prop="rating")
        assert len(back) == 4
        client2.close()


class TestParallelBulkAppend:
    """The multi-super-batch threaded render path of
    pio_evlog_append_interactions (eventlog.cc): >2M events span two
    super-batches, and PIO_NATIVE_THREADS forces the thread pool on."""

    N = 2_100_000  # crosses the 2M super-batch boundary

    def _import(self, tmp_path, monkeypatch, threads):
        from incubator_predictionio_tpu.data.storage.base import (
            IdTable,
            Interactions,
        )

        monkeypatch.setenv("PIO_NATIVE_THREADS", str(threads))
        # keep the projection cache out of the way: this test targets the
        # native append + scan, not the cache fold (setattr, not reload —
        # a reload would leak the changed MIN_NNZ to later test modules)
        from incubator_predictionio_tpu.data.storage import traincache
        monkeypatch.setattr(traincache, "MIN_NNZ", self.N * 10)
        rng = np.random.default_rng(3)
        nu, ni = 5_000, 1_200
        users = rng.integers(0, nu, self.N).astype(np.int32)
        items = rng.integers(0, ni, self.N).astype(np.int32)
        vals = rng.random(self.N).astype(np.float32)
        inter = Interactions(
            user_idx=users, item_idx=items, values=vals,
            user_ids=IdTable.from_list([f"u{k}" for k in range(nu)]),
            item_ids=IdTable.from_list([f"i{k}" for k in range(ni)]),
        )
        client = _client(tmp_path)
        events = _events(client)
        n = events.import_interactions(
            inter, 1, event_name="rate", value_prop="rating",
            base_time=T0)
        assert n == self.N
        out = events.scan_interactions(
            app_id=1, entity_type="user", target_entity_type="item",
            event_names=("rate",), value_prop="rating")
        client.close()
        return users, items, vals, out

    def test_two_superbatches_threaded_roundtrip(self, tmp_path,
                                                 monkeypatch):
        users, items, vals, out = self._import(tmp_path, monkeypatch, 4)
        assert len(out) == self.N
        # scan returns events in append (= time) order with first-seen
        # interned ids; translate back and compare exactly
        u_names = np.array([f"u{k}" for k in range(5_000)])
        got_users = np.asarray(out.user_ids.tolist())[out.user_idx]
        assert (got_users == u_names[users]).all()
        i_names = np.array([f"i{k}" for k in range(1_200)])
        got_items = np.asarray(out.item_ids.tolist())[out.item_idx]
        assert (got_items == i_names[items]).all()
        np.testing.assert_allclose(out.values, vals, rtol=1e-6)

    def test_threaded_matches_single_thread_bytes(self, tmp_path,
                                                  monkeypatch):
        # determinism: the rendered log must be byte-identical no matter
        # how many threads rendered it (same seed → same event ids)
        import hashlib

        d1, d4 = tmp_path / "t1", tmp_path / "t4"
        d1.mkdir(), d4.mkdir()
        from incubator_predictionio_tpu.data.storage.base import (
            IdTable,
            Interactions,
        )

        rng = np.random.default_rng(5)
        n = 200_000
        from incubator_predictionio_tpu.data.storage import traincache
        monkeypatch.setattr(traincache, "MIN_NNZ", n * 10)
        inter = Interactions(
            user_idx=rng.integers(0, 50, n).astype(np.int32),
            item_idx=rng.integers(0, 20, n).astype(np.int32),
            values=rng.random(n).astype(np.float32),
            user_ids=IdTable.from_list([f"u{k}" for k in range(50)]),
            item_ids=IdTable.from_list([f"i{k}" for k in range(20)]),
        )

        def run(path, threads):
            monkeypatch.setenv("PIO_NATIVE_THREADS", str(threads))
            client = _client(path)
            events = _events(client)
            # fixed base time AND fixed id seed → byte-identical logs
            events.import_interactions(
                inter, 1, event_name="rate", value_prop="rating",
                base_time=T0, id_seed=12345)
            client.close()
            return [
                (p.name, hashlib.sha256(p.read_bytes()).hexdigest())
                for p in sorted(path.iterdir())
            ]

        assert run(d1, 1) == run(d4, 4)


class TestRandomTruncationRecovery:
    """Crash-at-any-byte durability: truncating the log at EVERY possible
    cut point (or a random sample at scale) must reopen to a clean prefix
    of whole events, never a crash, never a partial record, and appends
    after recovery must frame correctly."""

    def test_every_cut_point_recovers_prefix(self, tmp_path):
        import shutil

        base = tmp_path / "orig"
        base.mkdir()
        c1 = _client(base)
        d1 = _events(c1)
        d1.init(1)
        ids = [d1.insert(ev(minutes=i, eid=f"u{i}"), 1) for i in range(3)]
        c1.close()
        log_file = next(base.glob("*.log"))
        blob = log_file.read_bytes()

        # EVERY byte offset is a cut point (3 records keep the blob small
        # enough to be exhaustive — a sampled test left header regions
        # permanently unexercised under a fixed seed)
        cuts = range(len(blob) + 1)
        prev_count = -1
        for cut in cuts:
            work = tmp_path / f"cut{cut}"
            shutil.copytree(base, work)
            wf = next(work.glob("*.log"))
            wf.write_bytes(blob[:cut])
            c = _client(work)
            d = _events(c)
            found = [e.event_id for e in d.find(app_id=1)]
            # always a strict prefix of the original insert order, and
            # monotone in the cut position (cuts iterate ascending)
            assert found == ids[:len(found)]
            assert len(found) >= prev_count
            # recovery is physical: the file holds only whole records now,
            # and a post-recovery append survives another reopen
            extra = d.insert(ev(minutes=99, eid="u99"), 1)
            c.close()
            c2 = _client(work)
            found2 = [e.event_id for e in _events(c2).find(app_id=1)]
            assert found2 == ids[:len(found)] + [extra]
            c2.close()
            prev_count = len(found)


class TestUniformBatchFastPath:
    """insert_batch routes uniform id-less interaction batches through the
    columnar import; the returned ids must be the ones the log stored
    (derived in Python from the same id_seed formula as eventlog.cc)."""

    def _batch(self, n, name="rate"):
        return [ev(name=name, eid=f"u{k % 5}", minutes=k,
                   target=f"i{k % 3}", props={"rating": float(k % 4)})
                for k in range(n)]

    def test_fast_path_ids_resolve_and_scan_matches(self, tmp_path):
        c = _client(tmp_path)
        d = _events(c)
        d.init(1)
        ids = d.insert_batch(self._batch(20), 1)
        assert len(ids) == 20 and len(set(ids)) == 20
        for k, eid in enumerate(ids):
            got = d.get(eid, 1)
            assert got is not None and got.event_id == eid
            assert got.entity_id == f"u{k % 5}"
            assert got.properties.get("rating") == float(k % 4)
        inter = d.scan_interactions(
            app_id=1, entity_type="user", target_entity_type="item",
            event_names=("rate",), value_prop="rating")
        assert len(inter) == 20
        # delete through a derived id works like any other id
        assert d.delete(ids[3], 1)
        assert d.get(ids[3], 1) is None
        c.close()

    def test_non_utc_batches_take_the_generic_path(self, tmp_path):
        """Compact columnar records store only epoch millis and re-render
        eventTime as UTC, so a uniform batch carrying a non-UTC offset
        (e.g. +09:00) must fall back to the generic path — same screen as
        the CLI import gate — or the timezone silently vanishes on
        read-back (other backends preserve tzinfo)."""
        import dataclasses
        from datetime import timezone as _tz

        c = _client(tmp_path)
        d = _events(c)
        d.init(1)
        jst = _tz(timedelta(hours=9))
        batch = [
            dataclasses.replace(e, event_time=e.event_time.astimezone(jst))
            for e in self._batch(12)
        ]
        ids = d.insert_batch(batch, 1)
        assert len(ids) == 12
        for src, eid in zip(batch, ids):
            got = d.get(eid, 1)
            assert got is not None
            assert got.event_time == src.event_time
            # the offset itself survives, not just the instant
            assert got.event_time.utcoffset() == timedelta(hours=9)
        c.close()

    def test_non_uniform_batches_take_the_generic_path(self, tmp_path):
        c = _client(tmp_path)
        d = _events(c)
        d.init(1)
        mixed = self._batch(10)
        mixed[4] = ev(name="view", eid="u1", minutes=4, target="i1",
                      props={"rating": 1.0})  # breaks uniformity
        ids = d.insert_batch(mixed, 1)
        assert len(ids) == 10
        assert all(d.get(e, 1) is not None for e in ids)
        # explicit ids also force the generic (upsert-capable) path
        explicit = [e.with_id(f"{k:032d}") for k, e in
                    enumerate(self._batch(10))]
        ids2 = d.insert_batch(explicit, 1)
        assert ids2 == [f"{k:032d}" for k in range(10)]
        c.close()
