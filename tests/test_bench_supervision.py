"""TPU-child supervision: abandoned-waiter pile-up guards.

A wedged single-tenant lease makes SIGTERM-immune waiters queue up (the
PJRT dial retry swallows signals inside the C call); when the lease
frees, the waiters claim it one after another. Only the first claimer
may run the TPU leg — every later claimer must exit immediately and
release the chip. These tests drive the real ``--tpu-child`` subprocess
on the CPU backend, where the dial succeeds instantly and the guards are
the first code after it.
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def _child_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    return env


def test_child_exits_without_running_when_fragment_exists(tmp_path):
    out_path = tmp_path / "fragment.json"
    out_path.write_text(json.dumps({"value": 1.0}))
    claim = tmp_path / "claim"
    store = tmp_path / "store"
    store.mkdir()
    proc = subprocess.run(
        [sys.executable, BENCH, "--tpu-child", str(store), str(out_path),
         str(claim), str(os.getpid())],
        env=_child_env(), capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    # exited at the guard: no claim written, fragment untouched
    assert not claim.exists()
    assert json.loads(out_path.read_text()) == {"value": 1.0}
    assert "already landed" in proc.stderr


def test_orphaned_child_exits_without_claiming(tmp_path):
    out_path = tmp_path / "fragment.json"
    claim = tmp_path / "claim"
    store = tmp_path / "store"
    store.mkdir()
    pidfile = tmp_path / "pid"
    childlog = tmp_path / "child.log"
    # the intermediate shell passes ITS pid as the parent handshake and
    # exits immediately; by the time the guard runs the child has been
    # reparented (to init or a subreaper — either way getppid() no
    # longer matches the handshake pid)
    subprocess.run(
        ["sh", "-c",
         f"{sys.executable} {BENCH} --tpu-child {store} {out_path} "
         f"{claim} $$ >{childlog} 2>&1 & echo $! > {pidfile}"],
        env=_child_env(), timeout=30, check=True)
    pid = int(pidfile.read_text().strip())
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            break
        time.sleep(1)
    else:
        raise AssertionError("orphaned tpu child still alive after 120s")
    # exited AT THE GUARD (not via some startup crash): the log proves
    # the orphan branch ran, and no claim/fragment was written
    assert "orphaned waiter" in childlog.read_text()
    assert not claim.exists()
    assert not out_path.exists()


class _FakeProc:
    """A Popen stand-in that never claims and never exits on its own —
    the wedged-lease dial waiter, minus the 870 s of waiting."""

    def __init__(self, *a, **kw):
        self.terminated = False

    def poll(self):
        return 0 if self.terminated else None

    def terminate(self):
        self.terminated = True

    def wait(self, timeout=None):
        return 0 if self.terminated else None


def test_supervisor_deadline_caps_claim_wait(tmp_path, monkeypatch):
    """The BENCH_r05 fix: the cumulative claim wait must respect the
    global deadline — the supervisor returns (terminating the unclaimed
    waiter) instead of recycling past it, so the orchestrator can emit
    its degraded record before the driver's kill."""
    import bench

    spawned = []

    def fake_popen(*a, **kw):
        spawned.append(_FakeProc())
        return spawned[-1]

    monkeypatch.setattr(bench.subprocess, "Popen", fake_popen)
    t0 = time.monotonic()
    ok = bench.supervise_tpu_child(
        str(tmp_path / "store"), str(tmp_path / "frag.json"),
        deadline_mono=time.monotonic() + 4.0)
    elapsed = time.monotonic() - t0
    assert ok is False
    assert elapsed < 60, elapsed  # returned at the deadline, not 180 s+
    assert spawned and spawned[-1].terminated  # waiter stopped (safe)


def test_supervisor_deadline_leaves_claimed_child_running(tmp_path,
                                                          monkeypatch):
    """Past the deadline with a CLAIMED child mid-run, the supervisor
    must return without terminating it — a chip holder is never cut
    down — and report whatever fragment exists."""
    import bench

    out_path = str(tmp_path / "frag.json")
    procs = []

    class _ClaimingProc(_FakeProc):
        def poll(self):
            # claim file appears on the first poll, as if the dial landed
            with open(f"{out_path}.claim1", "w") as f:
                f.write("1")
            return super().poll()

    def fake_popen(*a, **kw):
        procs.append(_ClaimingProc())
        return procs[-1]

    monkeypatch.setattr(bench.subprocess, "Popen", fake_popen)
    t0 = time.monotonic()
    ok = bench.supervise_tpu_child(
        str(tmp_path / "store"), out_path,
        deadline_mono=time.monotonic() + 3.0)
    assert time.monotonic() - t0 < 60
    assert ok is False  # no fragment landed
    assert procs and not procs[-1].terminated  # holder left running
