"""Mosaic block-shape rules, enforced on the CPU mesh.

The TPU lowering requires each of the LAST TWO dims of a VMEM block
shape to be sublane/lane aligned (multiples of 8 / 128) OR equal to the
corresponding array dim. Interpret-mode tests cannot catch violations —
this round's fused ALS kernel shipped with a sublane-1 aux block that
only failed on real hardware. This suite captures every
``pallas_call``'s (block shape, array shape) pairs while running the
kernels in interpret mode and checks the rule statically, so the bug
class is caught in CI without a chip.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

SUBLANE, LANE = 8, 128


def _dim_ok(block: int, array: int, quantum: int) -> bool:
    return block % quantum == 0 or block == array


def _check_pairs(pairs):
    assert pairs, "no pallas_call captured — the kernel under test moved"
    bad = []
    for name, block, array in pairs:
        if block is None or len(block) < 2:
            continue
        b2, b1 = block[-2], block[-1]
        a2, a1 = array[-2], array[-1]
        if not (_dim_ok(b2, a2, SUBLANE) and _dim_ok(b1, a1, LANE)):
            bad.append((name, tuple(block), tuple(array)))
    assert not bad, f"Mosaic-illegal blocks: {bad}"


@pytest.fixture
def capture(monkeypatch):
    """Record (operand, block_shape, array_shape) for every pallas_call
    issued under the fixture, while still executing it."""
    captured = []
    real = pl.pallas_call

    def spy(kernel, **kw):
        inner = real(kernel, **kw)

        def wrapped(*args):
            in_specs = kw.get("in_specs") or []
            for i, (spec, arg) in enumerate(zip(in_specs, args)):
                captured.append(
                    (f"in{i}", getattr(spec, "block_shape", None),
                     jnp.shape(arg)))
            out_specs = kw.get("out_specs")
            out_shape = kw.get("out_shape")
            if out_specs is not None and out_shape is not None:
                outs = (out_specs if isinstance(out_specs, (list, tuple))
                        else [out_specs])
                shapes = (out_shape if isinstance(out_shape, (list, tuple))
                          else [out_shape])
                for i, (spec, sh) in enumerate(zip(outs, shapes)):
                    captured.append(
                        (f"out{i}", getattr(spec, "block_shape", None),
                         tuple(sh.shape)))
            return inner(*args)

        return wrapped

    # pallas_kernels does `from jax.experimental import pallas as pl`,
    # so patching the shared module object covers its call sites too
    monkeypatch.setattr(pl, "pallas_call", spy)
    return captured


@pytest.mark.parametrize("rows", [1, 8])
@pytest.mark.parametrize("B,D,K", [
    (24, 48, 64),      # lane-padded D and K
    (13, 1024, 32),    # multi-tile D, group padding
    (8, 300, 128),     # non-multiple D, full-lane K
])
def test_als_kernel_blocks_are_mosaic_legal(capture, rows, B, D, K):
    from incubator_predictionio_tpu.ops.pallas_kernels import (
        als_solve_cg_pallas,
    )

    rng = np.random.default_rng(0)
    table = jnp.asarray(rng.normal(0, 0.3, (200, K)).astype(np.float32))
    cols = jnp.asarray(rng.integers(0, 200, (B, D)).astype(np.int32))
    vals = jnp.asarray(rng.normal(3.5, 1.0, (B, D)).astype(np.float32))
    mask = jnp.asarray((rng.random((B, D)) < 0.8).astype(np.float32))
    als_solve_cg_pallas(table, cols, vals, mask, 0.1, True, 4,
                        interpret=True, rows_per_program=rows)
    _check_pairs(capture)


@pytest.mark.parametrize("rows", [1, 8])
@pytest.mark.parametrize("B,D,K", [
    (24, 48, 64),      # lane-padded D and K
    (13, 1024, 32),    # multi-tile D, group padding
])
def test_als_kernel_warmstart_blocks_are_mosaic_legal(capture, rows, B, D, K):
    """The warm-start variant is a DIFFERENT kernel (extra x0 BlockSpec +
    initial-residual matvec) — production runs it by default
    (PIO_ALS_CG_WARMSTART=1), so its block shapes need the same static
    Mosaic check as the cold kernel (the als_kernel_available/x0 probe
    gap class, ADVICE.md round 5)."""
    from incubator_predictionio_tpu.ops.pallas_kernels import (
        als_solve_cg_pallas,
    )

    rng = np.random.default_rng(1)
    table = jnp.asarray(rng.normal(0, 0.3, (200, K)).astype(np.float32))
    cols = jnp.asarray(rng.integers(0, 200, (B, D)).astype(np.int32))
    vals = jnp.asarray(rng.normal(3.5, 1.0, (B, D)).astype(np.float32))
    mask = jnp.asarray((rng.random((B, D)) < 0.8).astype(np.float32))
    x0 = jnp.asarray(rng.normal(0, 0.3, (B, K)).astype(np.float32))
    als_solve_cg_pallas(table, cols, vals, mask, 0.1, True, 4,
                        interpret=True, rows_per_program=rows, x0=x0)
    x0_specs = [p for p in capture if p[0] == f"in{3}"]
    assert x0_specs, "warm path did not add the x0 operand spec"
    _check_pairs(capture)


@pytest.mark.parametrize("implicit,warm", [
    (False, False), (False, True), (True, False), (True, True),
])
@pytest.mark.parametrize("B,D,K", [
    (9, 48, 24),       # lane-padded D and K, non-sublane table rows
    (5, 1024, 32),     # multi-tile D streaming
])
def test_als_fused_kernel_blocks_are_mosaic_legal(capture, implicit, warm,
                                                  B, D, K):
    """The fused gather+Gram+CG kernel in all four production variants
    (explicit/implicit × cold/warm — each a DIFFERENT kernel: the yty
    and x0 operands add BlockSpecs). The whole-table block is legal by
    block == array; every per-row aux rides the proven [B, 1, x]
    layout."""
    from incubator_predictionio_tpu.ops import als
    from incubator_predictionio_tpu.ops.pallas_kernels import (
        als_fused_solve_cg_pallas,
    )

    rng = np.random.default_rng(2)
    table = jnp.asarray(rng.normal(0, 0.3, (150, K)).astype(np.float32))
    cols = jnp.asarray(rng.integers(0, 150, (B, D)).astype(np.int32))
    vals = jnp.asarray(rng.normal(3.5, 1.0, (B, D)).astype(np.float32))
    mask = jnp.asarray((rng.random((B, D)) < 0.8).astype(np.float32))
    yty = (als._gram_all(table, jax.lax.Precision.HIGHEST)
           if implicit else None)
    x0 = (jnp.asarray(rng.normal(0, 0.3, (B, K)).astype(np.float32))
          if warm else None)
    als_fused_solve_cg_pallas(table, cols, vals, mask, 0.1, True, 4,
                              implicit=implicit, alpha=1.5, yty=yty,
                              x0=x0, interpret=True)
    _check_pairs(capture)


@pytest.mark.parametrize("S", [512, 2048])
def test_flash_attention_blocks_are_mosaic_legal(capture, S):
    from incubator_predictionio_tpu.ops.pallas_kernels import (
        flash_attention,
    )

    key = jax.random.key(0)
    q, k, v = (jax.random.normal(kk, (1, 4, S, 64), jnp.float32)
               for kk in jax.random.split(key, 3))
    flash_attention(q, k, v, causal=True, interpret=True)
    _check_pairs(capture)
