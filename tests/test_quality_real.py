"""Real-data RMSE regression bound (VERDICT r4 item 4).

Trains on the reference's bundled MovieLens sample — the only real
interaction data in this egress-free environment — read at run time from
the read-only reference tree (never copied into the repo; provenance:
/root/reference/examples/experimental/data/movielens.txt, the file the
reference's own movielens tutorials consume). Skips when the reference
tree is not mounted. Loader, split, and hyperparameters are bench.py's
own (shared code, not a copy), so the pinned bound always guards the
exact configuration the bench record reports."""

import os

import numpy as np
import pytest

import bench

pytestmark = pytest.mark.skipif(
    not os.path.exists(bench.MOVIELENS_SAMPLE),
    reason="reference movielens sample not available")


def test_movielens_stage_clears_pinned_bound():
    """The bench stage itself (same loader, split seed, rank/λ) must keep
    beating the pinned bound on real ratings (measured 1.024-1.076
    across seeds; a mis-regularized run measures >=1.31)."""
    out = bench.bench_movielens_quality()
    assert set(out) == {"movielens_rmse", "movielens_rmse_bound"}
    assert out["movielens_rmse"] is not None
    assert out["movielens_rmse"] <= out["movielens_rmse_bound"], out


def test_movielens_model_beats_constant_predictor():
    """...and the model is a real model: better than predicting the
    train-mean on the same 80/20 split the stage uses."""
    from incubator_predictionio_tpu.ops import als

    users, items, vals, n_u, n_i = bench.load_movielens_sample()
    rng = np.random.default_rng(0)
    perm = rng.permutation(len(vals))
    cut = int(0.8 * len(vals))
    tr, te = perm[:cut], perm[cut:]
    state, _ = als.als_train(
        users[tr], items[tr], vals[tr], n_u, n_i,
        rank=bench.MOVIELENS_RANK, iterations=10, l2=bench.MOVIELENS_L2,
        seed=0)
    rmse = als.rmse(state, users[te], items[te], vals[te])
    const = float(np.sqrt(np.mean((vals[te] - vals[tr].mean()) ** 2)))
    assert rmse < const, (rmse, const)


def test_unusable_sample_skips_not_crashes(monkeypatch, tmp_path):
    """A malformed sample (wrong format via PIO_BENCH_MOVIELENS) must
    yield the null record keys, never crash the orchestrator."""
    bad = tmp_path / "u.data"
    bad.write_text("1\t2\t3\t881250949\n")  # ML-100K tab format
    monkeypatch.setattr(bench, "MOVIELENS_SAMPLE", str(bad))
    out = bench.bench_movielens_quality()
    assert out == {"movielens_rmse": None, "movielens_rmse_bound": None}