"""End-to-end recommendation template: events → train → predict → eval.

Parity: the reference's quickstart flow (tests/pio_tests/tests.py
QuickStartTest) at unit scale.
"""

import numpy as np
import pytest

from incubator_predictionio_tpu.core import EngineParams, MetricEvaluator
from incubator_predictionio_tpu.core.evaluation import Evaluation
from incubator_predictionio_tpu.data.datamap import DataMap
from incubator_predictionio_tpu.data.event import Event
from incubator_predictionio_tpu.data.storage import App, Storage
from incubator_predictionio_tpu.models.recommendation import (
    ALSAlgorithmParams,
    DataSourceParams,
    PredictedResult,
    Query,
    RecommendationEngine,
)
from incubator_predictionio_tpu.models.recommendation.engine import PrecisionAtK
from incubator_predictionio_tpu.parallel.context import RuntimeContext
from incubator_predictionio_tpu.workflow import CoreWorkflow


@pytest.fixture(autouse=True)
def mem_storage():
    Storage.configure({
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    })
    yield
    Storage.reset()


@pytest.fixture
def seeded_app():
    """Block-structured ratings: users uA* love items iA*, users uB* love
    iB* — so recommendations are unambiguous."""
    Storage.get_meta_data_apps().insert(App(0, "recapp"))
    dao = Storage.get_events()
    app_id = Storage.get_meta_data_apps().get_by_name("recapp").id
    rng = np.random.default_rng(0)
    events = []
    for g, (users, items) in enumerate(
        ((["uA%d" % i for i in range(8)], ["iA%d" % i for i in range(6)]),
         (["uB%d" % i for i in range(8)], ["iB%d" % i for i in range(6)]))
    ):
        for u in users:
            for it in items:
                if rng.random() < 0.7:
                    events.append(Event(
                        event="rate", entity_type="user", entity_id=u,
                        target_entity_type="item", target_entity_id=it,
                        properties=DataMap({"rating": float(rng.integers(4, 6))}),
                    ))
        # cross-group low ratings
        for u in users:
            other = "iB0" if g == 0 else "iA0"
            events.append(Event(
                event="rate", entity_type="user", entity_id=u,
                target_entity_type="item", target_entity_id=other,
                properties=DataMap({"rating": 1.0}),
            ))
    # one "buy" event (implicit 4.0)
    events.append(Event(event="buy", entity_type="user", entity_id="uA0",
                        target_entity_type="item", target_entity_id="iA5"))
    # item metadata for the custom-query filter
    for i in range(6):
        events.append(Event(
            event="$set", entity_type="item", entity_id="iA%d" % i,
            properties=DataMap({"creationYear": 1990 + i,
                                "categories": ["groupA"]}),
        ))
    for e in events:
        dao.insert(e, app_id)
    return app_id


def engine_params(eval_k=0, iters=10):
    return EngineParams(
        data_source_params=("", DataSourceParams(app_name="recapp",
                                                 eval_k=eval_k)),
        algorithm_params_list=[
            ("als", ALSAlgorithmParams(rank=8, num_iterations=iters,
                                       lambda_=0.05, seed=42))
        ],
    )


def test_train_and_predict(seeded_app):
    engine = RecommendationEngine().apply()
    ctx = RuntimeContext()
    models = engine.train(ctx, engine_params())
    algo = engine.algorithms(engine_params())[0]
    result = algo.predict(models[0], Query(user="uA1", num=3))
    assert len(result.item_scores) == 3
    # group-A user gets group-A items
    assert all(s.item.startswith("iA") for s in result.item_scores)
    # scores descending
    scores = [s.score for s in result.item_scores]
    assert scores == sorted(scores, reverse=True)


def test_unknown_user_empty_result(seeded_app):
    engine = RecommendationEngine().apply()
    models = engine.train(RuntimeContext(), engine_params())
    algo = engine.algorithms(engine_params())[0]
    assert algo.predict(models[0], Query(user="ghost", num=3)).item_scores == ()


def test_query_filters(seeded_app):
    engine = RecommendationEngine().apply()
    models = engine.train(RuntimeContext(), engine_params())
    algo = engine.algorithms(engine_params())[0]
    # creationYear filter: only iA3+ (1993+) qualify
    r = algo.predict(models[0], Query(user="uA1", num=6, creation_year=1993))
    assert r.item_scores
    assert all(s.creation_year and s.creation_year >= 1993 for s in r.item_scores)
    # category filter
    r = algo.predict(models[0], Query(user="uB1", num=4,
                                      categories=("groupA",)))
    assert all(s.item.startswith("iA") for s in r.item_scores)
    # whitelist / blacklist
    r = algo.predict(models[0], Query(user="uA1", num=4,
                                      whitelist=("iA0", "iA1")))
    assert {s.item for s in r.item_scores} <= {"iA0", "iA1"}
    r = algo.predict(models[0], Query(user="uA1", num=10, blacklist=("iA0",)))
    assert "iA0" not in {s.item for s in r.item_scores}


def test_full_workflow_train_store_reload(seeded_app):
    engine = RecommendationEngine().apply()
    iid = CoreWorkflow.run_train(engine, engine_params(),
                                 engine_variant="rec-test")
    models = CoreWorkflow.load_models(iid, engine, engine_params())
    algo = engine.algorithms(engine_params())[0]
    result = algo.predict(models[0], Query(user="uA2", num=2))
    assert len(result.item_scores) == 2


def test_batch_predict_matches_single(seeded_app):
    engine = RecommendationEngine().apply()
    models = engine.train(RuntimeContext(), engine_params())
    algo = engine.algorithms(engine_params())[0]
    queries = [(i, Query(user=u, num=3)) for i, u in
               enumerate(["uA0", "uB0", "ghost"])]
    batch = dict(algo.batch_predict(models[0], queries))
    for qx, q in queries:
        single = algo.predict(models[0], q)
        assert [s.item for s in batch[qx].item_scores] == \
               [s.item for s in single.item_scores]


def test_evaluation_precision_at_k(seeded_app):
    engine = RecommendationEngine().apply()
    evaluation = Evaluation()
    evaluation.engine_metric = (engine, PrecisionAtK(k=3))
    iid, result = CoreWorkflow.run_evaluation(
        evaluation, [engine_params(eval_k=2, iters=5)],
    )
    assert 0.0 <= result.best_score.score <= 1.0
    # block structure should make precision decent
    assert result.best_score.score > 0.2


def test_wire_format_parity():
    """Reference clients speak camelCase (Engine.scala:23-28 JSON)."""
    from incubator_predictionio_tpu.utils import json_codec

    q = json_codec.extract(Query, {"user": "u1", "num": 4,
                                   "creationYear": 1995})
    assert q.creation_year == 1995
    from incubator_predictionio_tpu.models.recommendation import ItemScore
    out = json_codec.to_jsonable(
        PredictedResult(item_scores=(ItemScore("i1", 1.5, 1990),))
    )
    assert out == {"itemScores": [
        {"item": "i1", "score": 1.5, "creationYear": 1990}
    ]}


def test_train_with_model_parallelism_matches_single(seeded_app):
    """ctx.model_parallelism > 1 routes through als_train_sharded (the
    mp-sharded ALX layout) and must produce the same model (the tests run
    on the virtual 8-device CPU mesh)."""
    engine = RecommendationEngine().apply()
    ref = engine.train(RuntimeContext(), engine_params())
    mp = engine.train(RuntimeContext(model_parallelism=2), engine_params())
    import numpy as np
    # tolerance: sharding changes the CG matvec reduction order, so the two
    # runs differ by the solver residual (~1e-5/solve at the default 16
    # iterations) amplified across the 10 alternating sweeps
    np.testing.assert_allclose(
        np.asarray(ref[0].user_factors), np.asarray(mp[0].user_factors),
        rtol=2e-3, atol=2e-4)
    algo = engine.algorithms(engine_params())[0]
    result = algo.predict(mp[0], Query(user="uA1", num=3))
    assert all(s.item.startswith("iA") for s in result.item_scores)


def test_host_and_device_serving_paths_agree(seeded_app):
    """Small models serve from a host factor copy; forcing the device path
    must give identical rankings (same scoring, same filters)."""
    engine = RecommendationEngine().apply()
    models = engine.train(RuntimeContext(), engine_params())
    algo = engine.algorithms(engine_params())[0]
    q = Query(user="uA1", num=3, exclude_seen=True)
    host = algo.predict(models[0], q)
    object.__setattr__(models[0], "_np_cache", False)  # force device path
    dev = algo.predict(models[0], q)
    assert [s.item for s in host.item_scores] == \
           [s.item for s in dev.item_scores]
    for a, b in zip(host.item_scores, dev.item_scores):
        assert abs(a.score - b.score) < 1e-4


def test_num_zero_returns_empty_on_both_paths(seeded_app):
    engine = RecommendationEngine().apply()
    models = engine.train(RuntimeContext(), engine_params())
    algo = engine.algorithms(engine_params())[0]
    assert algo.predict(models[0], Query(user="uA1", num=0)).item_scores == ()
    object.__setattr__(models[0], "_np_cache", False)
    assert algo.predict(models[0], Query(user="uA1", num=0)).item_scores == ()
