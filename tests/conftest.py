"""Test configuration.

Multi-chip behavior is tested on a virtual 8-device CPU mesh, mirroring how
the reference simulates its cluster with ``local[4]`` Spark
(reference: core/src/test/.../workflow/BaseTest.scala:71-88). These env vars
must be set before the first ``import jax`` anywhere in the test process.
"""

import os

# Must be set before the first jax import anywhere in the test process. The
# environment may pin JAX_PLATFORMS=axon (real TPU) via sitecustomize, which
# registers the backend at interpreter start — so overriding the env var is
# not enough; the config update below re-selects CPU before backends
# initialize (they are lazy).
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Multi-device simulation guard: if some plugin imported + initialized
# jax BEFORE this conftest could set XLA_FLAGS, the force-host-device
# flag never took and every sharded-path test would silently run on one
# device. Re-exec the test process ONCE with the flag exported so the
# backend initializes at 8 virtual devices (opt out: PIO_TEST_REEXEC=0).
import sys  # noqa: E402

if (os.environ.get("PIO_TEST_REEXEC", "1") != "0"
        and not os.environ.get("_PIO_TEST_REEXECED")
        and jax.device_count() == 1):
    os.environ["_PIO_TEST_REEXECED"] = "1"
    os.execv(sys.executable,
             [sys.executable, "-m", "pytest", *sys.argv[1:]])

import pytest  # noqa: E402


@pytest.fixture
def tmp_home(tmp_path, monkeypatch):
    """Isolated PIO home directory for storage-backed tests."""
    monkeypatch.setenv("PIO_HOME", str(tmp_path))
    return tmp_path


@pytest.fixture
def sub_mesh():
    """Mesh over the first N virtual devices — the sharded-path tests'
    seam for exercising mesh shapes {1, 2, 4, 8} on the CPU sim
    (parallel/mesh.py ``make_mesh``/``forced_device_count``)."""
    from incubator_predictionio_tpu.parallel.mesh import make_mesh

    def make(n: int, model_parallelism: int = 1):
        if jax.device_count() < n:
            pytest.skip(f"needs {n} devices")
        return make_mesh(devices=jax.devices()[:n],
                         model_parallelism=model_parallelism)

    return make
