"""Test configuration.

Multi-chip behavior is tested on a virtual 8-device CPU mesh, mirroring how
the reference simulates its cluster with ``local[4]`` Spark
(reference: core/src/test/.../workflow/BaseTest.scala:71-88). These env vars
must be set before the first ``import jax`` anywhere in the test process.
"""

import os

# Must be set before the first jax import anywhere in the test process. The
# environment may pin JAX_PLATFORMS=axon (real TPU) via sitecustomize, which
# registers the backend at interpreter start — so overriding the env var is
# not enough; the config update below re-selects CPU before backends
# initialize (they are lazy).
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def tmp_home(tmp_path, monkeypatch):
    """Isolated PIO home directory for storage-backed tests."""
    monkeypatch.setenv("PIO_HOME", str(tmp_path))
    return tmp_path
