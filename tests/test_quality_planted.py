"""Planted-ground-truth quality proof through the REAL eval workflow.

The r3 verdict: "model quality is asserted, not proven" — synthetic noise
benches only prove the solver ran. These tests plant a low-rank + noise
ground truth with a KNOWN recoverable structure and drive the actual
`pio eval` machinery (CoreWorkflow.run_evaluation → MetricEvaluator →
best.json, MetricEvaluator.scala:185's role):

- heldout RMSE must approach the planted noise floor (recovery),
- precision@k must find the planted ranking,
- the evaluator must *discriminate*: given a good and a crippled
  candidate, best.json must carry the good one.
"""

import json

import numpy as np
import pytest

from incubator_predictionio_tpu.core import EngineParams, MetricEvaluator
from incubator_predictionio_tpu.core.evaluation import Evaluation
from incubator_predictionio_tpu.data.datamap import DataMap
from incubator_predictionio_tpu.data.event import Event
from incubator_predictionio_tpu.data.storage import App, Storage
from incubator_predictionio_tpu.models.recommendation import (
    ALSAlgorithmParams,
    DataSourceParams,
    Query,
    RecommendationEngine,
)
from incubator_predictionio_tpu.models.recommendation.engine import (
    PrecisionAtK,
)
from incubator_predictionio_tpu.parallel.context import RuntimeContext
from incubator_predictionio_tpu.workflow import CoreWorkflow

N_USERS, N_ITEMS, PLANT_RANK = 60, 40, 3
SIGMA = 0.2
DENSITY = 0.5


@pytest.fixture(autouse=True)
def mem_storage():
    Storage.configure({
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    })
    yield
    Storage.reset()


@pytest.fixture
def planted():
    """Ratings = 3.5 + U·Vᵀ + N(0, σ) seeded into the event store;
    returns (app_id, U, V, observed-(u,i) set).

    Observation is preference-biased (each user rates items with
    probability ∝ softmax of the true score) — the property of real
    ratings data that makes held-out precision@k a DISCRIMINATING metric:
    under uniform observation, hits are observation-driven and every
    model scores at the chance floor."""
    rng = np.random.default_rng(11)
    u_true = rng.normal(0, 1 / np.sqrt(PLANT_RANK),
                        (N_USERS, PLANT_RANK))
    v_true = rng.normal(0, 1.0, (N_ITEMS, PLANT_RANK))
    Storage.get_meta_data_apps().insert(App(0, "planted"))
    app_id = Storage.get_meta_data_apps().get_by_name("planted").id
    dao = Storage.get_events()
    per_user = int(DENSITY * N_ITEMS)
    users_l, items_l = [], []
    for u in range(N_USERS):
        scores = u_true[u] @ v_true.T
        w = np.exp(2.0 * (scores - scores.max()))
        picks = rng.choice(N_ITEMS, size=per_user, replace=False,
                           p=w / w.sum())
        users_l.extend([u] * per_user)
        items_l.extend(picks.tolist())
    users = np.asarray(users_l)
    items = np.asarray(items_l)
    ratings = (3.5 + np.einsum("nk,nk->n", u_true[users], v_true[items])
               + rng.normal(0, SIGMA, len(users)))
    for u, i, r in zip(users, items, ratings):
        dao.insert(Event(
            event="rate", entity_type="user", entity_id=f"u{u}",
            target_entity_type="item", target_entity_id=f"i{i}",
            properties=DataMap({"rating": float(r)}),
        ), app_id)
    return app_id, u_true, v_true, set(zip(users.tolist(), items.tolist()))


def params(lambda_=0.05, rank=8, eval_k=0, iterations=12):
    return EngineParams(
        data_source_params=("", DataSourceParams(app_name="planted",
                                                 eval_k=eval_k)),
        algorithm_params_list=[
            ("als", ALSAlgorithmParams(rank=rank, num_iterations=iterations,
                                       lambda_=lambda_, seed=7))
        ],
    )


def test_heldout_rmse_recovers_noise_floor(planted):
    """Training on the observed half recovers the planted structure: RMSE
    on FRESH (user, item) pairs — never observed — approaches σ, far
    below the ratings' own stdev (≈ 1 + σ)."""
    app_id, u_true, v_true, seen = planted
    engine = RecommendationEngine().apply()
    model = engine.train(RuntimeContext(), params())[0]
    rng = np.random.default_rng(3)
    err, n = 0.0, 0
    uf = np.asarray(model.user_factors)
    vf = np.asarray(model.item_factors)
    for _ in range(2000):
        u = int(rng.integers(N_USERS))
        i = int(rng.integers(N_ITEMS))
        if (u, i) in seen:
            continue
        ui = model.user_bimap.get(f"u{u}")
        ii = model.item_bimap.get(f"i{i}")
        if ui is None or ii is None:
            continue
        true_rating = 3.5 + float(u_true[u] @ v_true[i])
        pred = float(uf[ui] @ vf[ii])
        err += (pred - true_rating) ** 2
        n += 1
    assert n > 300
    rmse = np.sqrt(err / n)
    # generalization ≈ noise floor (σ=0.2); the ratings themselves have
    # stdev ≈ 1.1, so anything near σ proves real structure recovery
    assert rmse < 2.5 * SIGMA, rmse


def test_eval_workflow_discriminates_and_writes_best_json(planted, tmp_path):
    """pio eval parity: MetricEvaluator scores a good candidate against an
    over-regularized one, picks the good one, and writes best.json."""
    app_id, *_ = planted
    best_path = tmp_path / "best.json"
    engine = RecommendationEngine().apply()
    evaluation = Evaluation()
    evaluation.engine_evaluator = (
        engine,
        MetricEvaluator(PrecisionAtK(k=5), output_path=str(best_path)),
    )
    good = params(lambda_=0.05, eval_k=3)
    untrained = params(eval_k=3, iterations=0)  # random init factors
    iid, result = CoreWorkflow.run_evaluation(evaluation, [untrained, good])
    assert result.best_score.score > 0.35   # planted ranking is findable
    assert result.best_idx == 1             # ...and the evaluator knows it
    scores = [ms.score for _, ms in result.engine_params_scores]
    assert scores[1] > scores[0] + 0.1      # clear separation from chance
    written = json.loads(best_path.read_text())
    assert written == good.to_jsonable()    # best.json carries the winner
